"""Evaluation kernels for the linear recurrence behind every multistep estimator.

Every return/advantage estimator in ops/multistep.py reduces to ONE first-order
linear recurrence, scanned backwards over time:

    acc_t = delta_t + weight_t * acc_{t+1},        acc_T = init.

Each step is the affine map f_t(x) = delta_t + weight_t * x, and the answer at
time t is the suffix composition (f_t ∘ f_{t+1} ∘ ... ∘ f_{T-1})(init).
Composition of affine maps is associative —

    (w, d) ∘ (w', d') = (w·w', d + w·d')

— so the whole suffix family is computable in O(log T) depth instead of the
O(T) sequential chain a `lax.scan` emits. On a TPU the scan's T dependent
steps serialize the VPU; the log-depth form trades ~2x the flops for parallel
depth, which wins whenever T is larger than a few vector widths.

Three interchangeable implementations, selected per call or process-wide:

    scan    sequential `lax.scan` — the reference semantics, bit-identical to
            what every system shipped with (the default).
    assoc   `jax.lax.associative_scan` over the (weight, delta) pairs —
            log-depth, pure XLA, differs from `scan` only by float reassociation
            (float32 ≤1e-5 relative on RL-scale inputs; see tests).
    pallas  time-blocked Pallas TPU kernel: the sequential recurrence runs in
            VMEM block_t rows at a time with a cross-block carry, so HBM sees
            one stream read + one stream write instead of scan's per-step
            dispatch. Within a block the op ORDER is exactly `scan`'s, so
            float32 results are bit-identical to `scan` (the accumulator is
            fp32 even for bf16 inputs, which `scan` does not do — documented
            divergence for low-precision inputs). Off-TPU this impl falls back
            to `scan` (same values; the Pallas interpreter is far slower than
            XLA's scan on CPU — same posture as ops/pallas_attention.py).

`n`-step windowed folds (n_step_bootstrapped_returns) are not a suffix scan —
each output composes exactly n maps — so the `assoc`/`pallas` route uses
binary doubling over the window instead: O(log n) shifted compositions rather
than the reference's n unrolled vector passes.

The process-wide default is set once per run from `system.multistep_impl`
(systems/runner.py and the Sebulba learner both call `configure_from_config`
before any learner is traced); estimators also accept an explicit `impl=`
override. The default read is trace-time static: changing it never triggers a
recompile of an already-traced program.
"""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from stoix_tpu.ops.pallas_attention import _out_struct

Array = jax.Array

VALID_IMPLS = ("scan", "assoc", "pallas")

_DEFAULT_IMPL = "scan"


def _validate_impl(impl: str) -> str:
    if impl not in VALID_IMPLS:
        raise ValueError(
            f"unknown multistep impl {impl!r}; valid: {', '.join(VALID_IMPLS)}"
        )
    return impl


def set_default_impl(impl: str) -> str:
    """Set the process-wide default implementation; returns the previous one."""
    global _DEFAULT_IMPL
    previous = _DEFAULT_IMPL
    _DEFAULT_IMPL = _validate_impl(str(impl))
    return previous


def get_default_impl() -> str:
    return _DEFAULT_IMPL


def resolve_impl(impl: Optional[str]) -> str:
    """An explicit per-call impl wins; None means the process-wide default."""
    return _DEFAULT_IMPL if impl is None else _validate_impl(str(impl))


def configure_from_config(config: Any) -> str:
    """Read `system.multistep_impl` (default `scan`) and install it as the
    process default. Called by both architectures' run entry points BEFORE the
    learner is traced, so the estimators inside the jitted learner pick the
    configured kernel at trace time."""
    impl = str(config.system.get("multistep_impl", "scan"))
    set_default_impl(impl)
    return impl


@contextlib.contextmanager
def use_impl(impl: str) -> Iterator[str]:
    """Scoped default override (tests and benchmarks)."""
    previous = set_default_impl(impl)
    try:
        yield impl
    finally:
        set_default_impl(previous)


# ---------------------------------------------------------------------------
# scan: the reference sequential recurrence (bit-identity anchor)
# ---------------------------------------------------------------------------


def _scan_reverse(weight_t: Array, delta_t: Array, init: Array) -> Array:
    """acc_t = delta_t + weight_t * acc_{t+1}, scanned from T-1 down to 0.

    This is verbatim the pre-dispatch `multistep._reverse_scan` body; the
    `scan` impl must stay byte-for-byte this program (tests pin bitwise
    equality against an inlined copy)."""

    def body(acc: Array, inputs: Tuple[Array, Array]) -> Tuple[Array, Array]:
        delta, weight = inputs
        acc = delta + weight * acc
        return acc, acc

    _, out = jax.lax.scan(body, init, (delta_t, weight_t), reverse=True)
    return out


# ---------------------------------------------------------------------------
# assoc: log-depth suffix composition via jax.lax.associative_scan
# ---------------------------------------------------------------------------


def _suffix_compose(a: Tuple[Array, Array], b: Tuple[Array, Array]) -> Tuple[Array, Array]:
    """Combine for the REVERSE associative scan. With reverse=True the left
    argument is the already-combined suffix of LATER timesteps and the right
    argument is the current (earlier) element, whose map applies OUTERMOST:
    f_b ∘ f_a = (w_b·w_a, d_b + w_b·d_a)."""
    w_a, d_a = a
    w_b, d_b = b
    return w_b * w_a, d_b + w_b * d_a


def _assoc_reverse(weight_t: Array, delta_t: Array, init: Array) -> Array:
    w_cum, d_cum = jax.lax.associative_scan(
        _suffix_compose, (weight_t, delta_t), reverse=True, axis=0
    )
    # acc_t = F_t(init) where F_t is the composed suffix map at t.
    return d_cum + w_cum * init


# ---------------------------------------------------------------------------
# pallas: time-blocked sequential recurrence with a cross-block VMEM carry
# ---------------------------------------------------------------------------


def _recurrence_kernel(w_ref, d_ref, init_ref, o_ref, acc_ref, *, block_t: int):
    """One time block, walked bottom row up with the carry in VMEM scratch.

    The grid's time axis is iterated LAST-block-first (the index_map reverses
    it), and TPU grids execute sequentially, so `acc_ref` legally carries the
    accumulator across blocks; it is (re)seeded from `init_ref` at the first
    grid step of each batch block."""
    t_idx = pl.program_id(1)

    @pl.when(t_idx == 0)
    def _seed():
        acc_ref[:] = init_ref[:].astype(jnp.float32)

    def body(j, _):
        row = block_t - 1 - j
        acc = d_ref[row, :].astype(jnp.float32) + w_ref[row, :].astype(
            jnp.float32
        ) * acc_ref[0, :]
        acc_ref[0, :] = acc
        o_ref[row, :] = acc.astype(o_ref.dtype)
        return 0

    jax.lax.fori_loop(0, block_t, body, 0)


def _pad_tail(x: Array, axis: int, multiple: int, value: float) -> Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


@functools.partial(jax.jit, static_argnames=("block_t", "block_b", "interpret"))
def pallas_linear_recurrence_reverse(
    weight_t: Array,
    delta_t: Array,
    init: Array,
    block_t: int = 128,
    block_b: int = 128,
    interpret: bool = False,
) -> Array:
    """Time-blocked Pallas evaluation of the reverse linear recurrence.

    Accepts [T, ...] inputs (trailing dims flattened to one lane axis) with
    `init` shaped like one timestep. Time is padded with identity maps
    (w=1, d=0) — the padded rows are processed first and leave the carry at
    `init` — and the batch axis is padded to the lane width. The in-block op
    order is exactly `_scan_reverse`'s, with an fp32 accumulator.
    """
    orig_shape = delta_t.shape
    t_len = orig_shape[0]
    w2 = weight_t.reshape(t_len, -1)
    d2 = delta_t.reshape(t_len, -1)
    init2 = init.reshape(1, -1).astype(delta_t.dtype)
    b_len = d2.shape[1]

    block_t = min(block_t, max(8, t_len))
    w2 = _pad_tail(w2, 0, block_t, 1.0)  # identity maps keep acc = init
    d2 = _pad_tail(d2, 0, block_t, 0.0)
    w2 = _pad_tail(w2, 1, block_b, 1.0)
    d2 = _pad_tail(d2, 1, block_b, 0.0)
    init2 = _pad_tail(init2, 1, block_b, 0.0)
    t_pad, b_pad = d2.shape
    n_t, n_b = t_pad // block_t, b_pad // block_b

    out = pl.pallas_call(
        functools.partial(_recurrence_kernel, block_t=block_t),
        # Batch blocks outer, time blocks inner (reversed by the index_map):
        # each batch block finishes its full time walk before the next starts,
        # so the single scratch row is a valid carry for all of them.
        grid=(n_b, n_t),
        in_specs=[
            pl.BlockSpec((block_t, block_b), lambda i, j, nt=n_t: (nt - 1 - j, i)),
            pl.BlockSpec((block_t, block_b), lambda i, j, nt=n_t: (nt - 1 - j, i)),
            pl.BlockSpec((1, block_b), lambda i, j: (0, i)),
        ],
        out_specs=pl.BlockSpec((block_t, block_b), lambda i, j, nt=n_t: (nt - 1 - j, i)),
        out_shape=_out_struct((t_pad, b_pad), delta_t.dtype, w2, d2, init2),
        scratch_shapes=[pltpu.VMEM((1, block_b), jnp.float32)],
        # Both grid axes carry state through the scratch accumulator; neither
        # may be parallelized across cores.
        compiler_params=pltpu.TPUCompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        ),
        interpret=interpret,
    )(w2, d2, init2)
    return out[:t_len, :b_len].reshape(orig_shape)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def linear_recurrence_reverse(
    weight_t: Array, delta_t: Array, init: Array, impl: Optional[str] = None
) -> Array:
    """Suffix evaluation of acc_t = delta_t + weight_t * acc_{t+1} (acc_T =
    init) under the selected implementation. `impl=None` uses the process
    default (`system.multistep_impl`)."""
    impl = resolve_impl(impl)
    if impl == "assoc":
        return _assoc_reverse(weight_t, delta_t, init)
    if impl == "pallas":
        if jax.default_backend() == "tpu":
            return pallas_linear_recurrence_reverse(weight_t, delta_t, init)
        # Portable fallback: same values (the kernel's op order IS the scan's),
        # and XLA's scan beats the Pallas interpreter off-TPU by orders of
        # magnitude — the same posture as pallas_attention.best_attention.
        return _scan_reverse(weight_t, delta_t, init)
    return _scan_reverse(weight_t, delta_t, init)


# ---------------------------------------------------------------------------
# windowed n-step folds: binary doubling over the window length
# ---------------------------------------------------------------------------


def _shift_maps(w: Array, d: Array, k: int) -> Tuple[Array, Array]:
    """Maps advanced k steps toward the future, identity-padded at the tail."""
    if k == 0:
        return w, d
    ones = jnp.ones((k,) + w.shape[1:], w.dtype)
    zeros = jnp.zeros((k,) + d.shape[1:], d.dtype)
    return (
        jnp.concatenate([w[k:], ones], axis=0),
        jnp.concatenate([d[k:], zeros], axis=0),
    )


def affine_window_fold(weight: Array, delta: Array, boot: Array, n: int) -> Array:
    """targets[t] = (f_t ∘ f_{t+1} ∘ ... ∘ f_{t+n-1})(boot[t]) in O(log n)
    passes via binary doubling, where f_j(x) = delta[j] + weight[j]·x and maps
    past the end of `weight`/`delta` are identity.

    `weight`/`delta` are time-major of length L ≥ len(boot); the output has
    `boot`'s length. Matches the reference n-step unrolled loop (which is n
    sequential vector passes) up to float reassociation.
    """
    out_len = boot.shape[0]
    # R: composed prefix of the window (span r_span); P: stride-doubling maps.
    r_w = jnp.ones_like(weight)
    r_d = jnp.zeros_like(delta)
    r_span = 0
    p_w, p_d, p_span = weight, delta, 1
    remaining = int(n)
    while remaining:
        if remaining & 1:
            # Append P AFTER R's span: R'[t] = R[t] ∘ P[t + r_span].
            s_w, s_d = _shift_maps(p_w, p_d, r_span)
            r_w, r_d = r_w * s_w, r_d + r_w * s_d
            r_span += p_span
        remaining >>= 1
        if remaining:
            s_w, s_d = _shift_maps(p_w, p_d, p_span)
            p_w, p_d = p_w * s_w, p_d + p_w * s_d
            p_span *= 2
    return r_d[:out_len] + r_w[:out_len] * boot
