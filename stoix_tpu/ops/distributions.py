"""First-party probability distributions for policy heads.

The reference leans on distrax/tensorflow-probability (reference
stoix/networks/distributions.py, heads.py); neither is a dependency here, so
this module provides the needed surface natively in JAX:

    d.sample(seed=key)   d.log_prob(x)   d.entropy()   d.mode()   d.mean()
    d.kl_divergence(other)

All math is elementwise fp32 and shape-static so distributions can live inside
jit/scan/shard_map without tracing hazards. Distributions are plain Python
objects over traced arrays — they never cross a jit boundary.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Distribution:
    """Minimal distribution interface."""

    def sample(self, *, seed: jax.Array) -> jax.Array:
        raise NotImplementedError

    def sample_n(self, n: int, *, seed: jax.Array) -> jax.Array:
        keys = jax.random.split(seed, n)
        return jax.vmap(lambda k: self.sample(seed=k))(keys)

    def log_prob(self, value: jax.Array) -> jax.Array:
        raise NotImplementedError

    def entropy(self) -> jax.Array:
        raise NotImplementedError

    def mode(self) -> jax.Array:
        raise NotImplementedError

    def mean(self) -> jax.Array:
        raise NotImplementedError

    def sample_and_log_prob(self, *, seed: jax.Array):
        x = self.sample(seed=seed)
        return x, self.log_prob(x)

    def kl_divergence(self, other: "Distribution") -> jax.Array:
        raise NotImplementedError


class Categorical(Distribution):
    """Categorical over the last axis of `logits`, with optional action mask."""

    def __init__(self, logits: jax.Array, mask: Optional[jax.Array] = None):
        if mask is not None:
            neg_inf = jnp.finfo(logits.dtype).min
            logits = jnp.where(mask > 0, logits, neg_inf)
        self.logits = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)

    @property
    def num_categories(self) -> int:
        return self.logits.shape[-1]

    @property
    def probs(self) -> jax.Array:
        return jnp.exp(self.logits)

    def sample(self, *, seed: jax.Array) -> jax.Array:
        return jax.random.categorical(seed, self.logits, axis=-1)

    def log_prob(self, value: jax.Array) -> jax.Array:
        value = jnp.asarray(value, jnp.int32)
        return jnp.take_along_axis(self.logits, value[..., None], axis=-1)[..., 0]

    def entropy(self) -> jax.Array:
        p = self.probs
        return -jnp.sum(p * jnp.where(p > 0, self.logits, 0.0), axis=-1)

    def mode(self) -> jax.Array:
        return jnp.argmax(self.logits, axis=-1)

    def mean(self) -> jax.Array:
        return jnp.sum(self.probs * jnp.arange(self.num_categories), axis=-1)

    def kl_divergence(self, other: "Categorical") -> jax.Array:
        p = self.probs
        return jnp.sum(p * jnp.where(p > 0, self.logits - other.logits, 0.0), axis=-1)


def _mask_preferences(preferences: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    if mask is None:
        return preferences
    return jnp.where(mask > 0, preferences, jnp.finfo(preferences.dtype).min)


class EpsilonGreedy(Categorical):
    """Epsilon-greedy over Q-values — returned by DiscreteQNetworkHead so acting
    is `dist.sample(...)` uniformly across value- and policy-based systems
    (reference stoix/networks/heads.py:202-217 returns distrax.EpsilonGreedy).

    With a mask, the greedy argmax is taken over LEGAL actions only and the
    epsilon mass is spread uniformly over legal actions.
    """

    def __init__(self, preferences: jax.Array, epsilon: float, mask: Optional[jax.Array] = None):
        self.preferences = preferences
        self.epsilon = epsilon
        num = preferences.shape[-1]
        masked_prefs = _mask_preferences(preferences, mask)
        self._masked_preferences = masked_prefs
        greedy = jax.nn.one_hot(jnp.argmax(masked_prefs, axis=-1), num)
        if mask is None:
            uniform = jnp.ones_like(preferences) / num
        else:
            valid = (mask > 0).astype(preferences.dtype)
            uniform = valid / jnp.sum(valid, axis=-1, keepdims=True)
        probs = (1.0 - epsilon) * greedy + epsilon * uniform
        super().__init__(jnp.log(probs + 1e-12), mask=mask)

    def mode(self) -> jax.Array:
        return jnp.argmax(self._masked_preferences, axis=-1)


class Greedy(Categorical):
    def __init__(self, preferences: jax.Array, mask: Optional[jax.Array] = None):
        self.preferences = preferences
        masked_prefs = _mask_preferences(preferences, mask)
        self._masked_preferences = masked_prefs
        num = preferences.shape[-1]
        probs = jax.nn.one_hot(jnp.argmax(masked_prefs, axis=-1), num)
        super().__init__(jnp.log(probs + 1e-12), mask=mask)

    def mode(self) -> jax.Array:
        return jnp.argmax(self._masked_preferences, axis=-1)


class Normal(Distribution):
    def __init__(self, loc: jax.Array, scale: jax.Array):
        self.loc = loc
        self.scale = scale

    def sample(self, *, seed: jax.Array) -> jax.Array:
        eps = jax.random.normal(seed, jnp.shape(self.loc), dtype=jnp.result_type(self.loc))
        return self.loc + self.scale * eps

    def log_prob(self, value: jax.Array) -> jax.Array:
        z = (value - self.loc) / self.scale
        return -0.5 * z**2 - jnp.log(self.scale) - _HALF_LOG_2PI

    def entropy(self) -> jax.Array:
        return 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)

    def mode(self) -> jax.Array:
        return self.loc

    def mean(self) -> jax.Array:
        return self.loc

    def stddev(self) -> jax.Array:
        return self.scale

    def kl_divergence(self, other: "Normal") -> jax.Array:
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Independent(Distribution):
    """Sums log_prob/entropy/kl over the last `reinterpreted_batch_ndims` dims."""

    def __init__(self, distribution: Distribution, reinterpreted_batch_ndims: int = 1):
        self.distribution = distribution
        self._ndims = int(reinterpreted_batch_ndims)

    def _reduce(self, x: jax.Array) -> jax.Array:
        return jnp.sum(x, axis=tuple(range(-self._ndims, 0)))

    def sample(self, *, seed: jax.Array) -> jax.Array:
        return self.distribution.sample(seed=seed)

    def sample_and_log_prob(self, *, seed: jax.Array):
        x, lp = self.distribution.sample_and_log_prob(seed=seed)
        return x, self._reduce(lp)

    def log_prob(self, value: jax.Array) -> jax.Array:
        return self._reduce(self.distribution.log_prob(value))

    def entropy(self) -> jax.Array:
        return self._reduce(self.distribution.entropy())

    def mode(self) -> jax.Array:
        return self.distribution.mode()

    def mean(self) -> jax.Array:
        return self.distribution.mean()

    def stddev(self) -> jax.Array:
        return self.distribution.stddev()

    def kl_divergence(self, other: "Independent") -> jax.Array:
        return self._reduce(self.distribution.kl_divergence(other.distribution))


class MultivariateNormalDiag(Independent):
    def __init__(self, loc: jax.Array, scale_diag: jax.Array):
        super().__init__(Normal(loc, scale_diag), 1)
        self.loc = loc
        self.scale_diag = scale_diag


class Deterministic(Distribution):
    """A point mass — deterministic policies (DDPG/TD3) behind the same API."""

    def __init__(self, loc: jax.Array):
        self.loc = loc

    def sample(self, *, seed: jax.Array) -> jax.Array:
        del seed
        return self.loc

    def log_prob(self, value: jax.Array) -> jax.Array:
        return jnp.zeros(jnp.shape(self.loc)[:-1] if jnp.ndim(self.loc) else ())

    def entropy(self) -> jax.Array:
        return jnp.zeros(jnp.shape(self.loc)[:-1] if jnp.ndim(self.loc) else ())

    def mode(self) -> jax.Array:
        return self.loc

    def mean(self) -> jax.Array:
        return self.loc


class TanhNormal(Distribution):
    """tanh-squashed Normal, affinely rescaled to [minimum, maximum].

    Equivalent of the reference's `AffineTanhTransformedDistribution`
    (reference stoix/networks/distributions.py:24-95): log_prob is clipped at
    the boundaries (atanh diverges) via a `threshold` below the max action.
    """

    def __init__(
        self,
        loc: jax.Array,
        scale: jax.Array,
        minimum: jax.Array = -1.0,
        maximum: jax.Array = 1.0,
        threshold: float = 0.999,
    ):
        self.base = Normal(loc, scale)
        self._scale = (jnp.asarray(maximum) - jnp.asarray(minimum)) / 2.0
        self._shift = (jnp.asarray(maximum) + jnp.asarray(minimum)) / 2.0
        self._threshold = threshold

    def _forward(self, x: jax.Array) -> jax.Array:
        return jnp.tanh(x) * self._scale + self._shift

    def _inverse(self, y: jax.Array) -> jax.Array:
        u = (y - self._shift) / self._scale
        u = jnp.clip(u, -self._threshold, self._threshold)
        return jnp.arctanh(u)

    def _log_det_jacobian(self, x: jax.Array) -> jax.Array:
        # d/dx [scale * tanh(x)] = scale * (1 - tanh^2 x); numerically stable form.
        return jnp.log(self._scale) + 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))

    def sample(self, *, seed: jax.Array) -> jax.Array:
        return self._forward(self.base.sample(seed=seed))

    def sample_and_log_prob(self, *, seed: jax.Array):
        x = self.base.sample(seed=seed)
        y = self._forward(x)
        lp = self.base.log_prob(x) - self._log_det_jacobian(x)
        return y, lp

    def log_prob(self, value: jax.Array) -> jax.Array:
        x = self._inverse(value)
        return self.base.log_prob(x) - self._log_det_jacobian(x)

    def entropy(self) -> jax.Array:
        # Base entropy + expected log-det-jacobian at the mean (the reference's
        # single-sample estimator uses the mode; this matches distrax's approach
        # of estimating with one point).
        return self.base.entropy() + self._log_det_jacobian(self.base.loc)

    def mode(self) -> jax.Array:
        return self._forward(self.base.loc)

    def mean(self) -> jax.Array:
        return self._forward(self.base.loc)


class Beta(Distribution):
    """Beta(alpha, beta) on [0, 1], sampled via Gamma draws; `ClippedBeta`
    equivalent (reference distributions.py:97-113) clips samples away from
    exact 0/1 for log_prob stability.
    """

    _eps = 1e-6

    def __init__(self, alpha: jax.Array, beta: jax.Array):
        self.alpha = alpha
        self.beta = beta

    def sample(self, *, seed: jax.Array) -> jax.Array:
        k1, k2 = jax.random.split(seed)
        ga = jax.random.gamma(k1, self.alpha)
        gb = jax.random.gamma(k2, self.beta)
        x = ga / (ga + gb)
        return jnp.clip(x, self._eps, 1.0 - self._eps)

    def log_prob(self, value: jax.Array) -> jax.Array:
        a, b = self.alpha, self.beta
        lbeta = jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b) - jax.scipy.special.gammaln(a + b)
        return (a - 1) * jnp.log(value) + (b - 1) * jnp.log1p(-value) - lbeta

    def entropy(self) -> jax.Array:
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        lbeta = jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b) - jax.scipy.special.gammaln(a + b)
        return lbeta - (a - 1) * dg(a) - (b - 1) * dg(b) + (a + b - 2) * dg(a + b)

    def mode(self) -> jax.Array:
        a, b = self.alpha, self.beta
        interior = (a - 1) / jnp.maximum(a + b - 2, self._eps)
        return jnp.clip(jnp.where((a > 1) & (b > 1), interior, jnp.where(a >= b, 1.0, 0.0)), self._eps, 1 - self._eps)

    def mean(self) -> jax.Array:
        return self.alpha / (self.alpha + self.beta)


class AffineBeta(Independent):
    """Beta rescaled to an action interval [minimum, maximum]."""

    def __init__(self, alpha: jax.Array, beta: jax.Array, minimum: jax.Array, maximum: jax.Array):
        self._base = Beta(alpha, beta)
        self._lo = jnp.asarray(minimum)
        self._width = jnp.asarray(maximum) - jnp.asarray(minimum)
        super().__init__(self._base, 1)

    def _fwd(self, x: jax.Array) -> jax.Array:
        return self._lo + self._width * x

    def _inv(self, y: jax.Array) -> jax.Array:
        return jnp.clip((y - self._lo) / self._width, Beta._eps, 1 - Beta._eps)

    def sample(self, *, seed: jax.Array) -> jax.Array:
        return self._fwd(self._base.sample(seed=seed))

    def log_prob(self, value: jax.Array) -> jax.Array:
        return jnp.sum(self._base.log_prob(self._inv(value)) - jnp.log(self._width), axis=-1)

    def entropy(self) -> jax.Array:
        return jnp.sum(self._base.entropy() + jnp.log(self._width), axis=-1)

    def mode(self) -> jax.Array:
        return self._fwd(self._base.mode())

    def mean(self) -> jax.Array:
        return self._fwd(self._base.mean())


class DiscreteValued(Distribution):
    """A categorical over a fixed real-valued support — the distributional
    critic used by D4PG-style heads and the `DiscreteValuedTfpDistribution`
    (reference distributions.py:116-208). Exposes mean/variance over the support.
    """

    def __init__(self, logits: jax.Array, values: jax.Array):
        self.dist = Categorical(logits)
        self.values = values  # [num_atoms]

    @property
    def logits(self) -> jax.Array:
        return self.dist.logits

    @property
    def probs(self) -> jax.Array:
        return self.dist.probs

    def sample(self, *, seed: jax.Array) -> jax.Array:
        idx = self.dist.sample(seed=seed)
        return self.values[idx]

    def mean(self) -> jax.Array:
        return jnp.sum(self.probs * self.values, axis=-1)

    def variance(self) -> jax.Array:
        m = self.mean()
        return jnp.sum(self.probs * (self.values - m[..., None]) ** 2, axis=-1)

    def mode(self) -> jax.Array:
        return self.values[jnp.argmax(self.logits, axis=-1)]

    def entropy(self) -> jax.Array:
        return self.dist.entropy()


class MultiDiscrete(Distribution):
    """Factorized categorical over several discrete action dimensions
    (reference distributions.py:211-242): log_prob/entropy sum across dims.
    """

    def __init__(self, flat_logits: jax.Array, num_values: Sequence[int]):
        self.num_values = tuple(int(n) for n in num_values)
        self.dists = []
        start = 0
        for n in self.num_values:
            self.dists.append(Categorical(flat_logits[..., start : start + n]))
            start += n

    def sample(self, *, seed: jax.Array) -> jax.Array:
        keys = jax.random.split(seed, len(self.dists))
        return jnp.stack([d.sample(seed=k) for d, k in zip(self.dists, keys)], axis=-1)

    def log_prob(self, value: jax.Array) -> jax.Array:
        lps = [d.log_prob(value[..., i]) for i, d in enumerate(self.dists)]
        return sum(lps)

    def entropy(self) -> jax.Array:
        return sum(d.entropy() for d in self.dists)

    def mode(self) -> jax.Array:
        return jnp.stack([d.mode() for d in self.dists], axis=-1)

    def kl_divergence(self, other: "MultiDiscrete") -> jax.Array:
        return sum(a.kl_divergence(b) for a, b in zip(self.dists, other.dists))
