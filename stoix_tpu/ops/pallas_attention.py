"""Pallas TPU flash attention — the fused hot-op behind the transformer torso.

The pure-JAX `full_attention` (ops/ring_attention.py) materializes the full
[S, S] score matrix in HBM; XLA fuses some of it but the memory traffic still
scales O(S^2). This kernel runs the online-softmax recurrence entirely in
VMEM: each grid step holds one query block plus one (batch*head)'s K/V in
VMEM, streams K/V blocks through the MXU, and never writes scores to HBM —
attention becomes compute-bound on the MXU instead of HBM-bandwidth-bound.

Layout notes (see /opt/skills/guides/pallas_guide.md):
  - grid = (B*H, ceil(S / block_q)); one kernel instance owns one query block;
  - K/V for the (b, h) slice live in VMEM whole (S×D ≤ ~2 MB at S=8192, D=64,
    bf16) and are walked with `pl.ds` dynamic slices, block_k at a time;
  - accumulators (m, l, acc) are fp32 regardless of input dtype; all matmuls
    request `preferred_element_type=float32` so bf16 inputs still accumulate
    in fp32 on the MXU;
  - sequence padding to the block size is masked with statically-known
    lengths; causal masking uses 2-D `broadcasted_iota` (TPU needs ≥2-D iota).

`flash_attention` is a drop-in for `full_attention` ([B, S, H, D] in/out) and
is the default `attention_fn` for the transformer torso on TPU; on non-TPU
backends it falls back to the pure-JAX path (the Pallas interpreter is
orders of magnitude slower than XLA's fused attention on CPU, so the
fallback — not interpret mode — is the portable path; tests force interpret
mode explicitly to validate the kernel itself).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from stoix_tpu.ops.ring_attention import full_attention

_NEG_INF = float("-inf")


def _fold_block(q, k_blk, v_blk, mask, carry):
    """One K/V block folded into the online-softmax accumulator (m, l, acc).

    The single shared body for every kernel in this module — the -inf /
    finite-proxy guards live only here. `mask` may be None (no masking).
    q [Bq, D] is pre-scaled fp32; k_blk/v_blk [Bk, D] fp32."""
    m_acc, l_acc, acc = carry
    scores = jax.lax.dot_general(
        q, k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Bq, Bk]
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    m_blk = jnp.max(scores, axis=-1, keepdims=True)  # [Bq, 1]
    m_new = jnp.maximum(m_acc, m_blk)
    # Rows with nothing unmasked yet keep -inf; exp(-inf - -inf) is NaN,
    # so shift by a finite proxy and zero the weights via the mask.
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(scores - m_safe)  # [Bq, Bk]
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    alpha = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_safe), 0.0)
    l_new = l_acc * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p, v_blk,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [Bq, D]
    return m_new, l_new, acc * alpha + pv


def _init_carry(block_q: int, head_dim: int):
    return (
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
        jnp.zeros((block_q, head_dim), jnp.float32),
    )


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *, scale: float, block_k: int, causal: bool, kv_len: int
):
    block_q, head_dim = q_ref.shape
    s_pad = k_ref.shape[0]
    num_kv_blocks = s_pad // block_k

    q = q_ref[:].astype(jnp.float32) * scale  # [Bq, D]
    q_block_idx = pl.program_id(1)
    q_pos = q_block_idx * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(j, carry):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        k_pos = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < kv_len  # strip the padded tail
        if causal:
            mask = jnp.logical_and(mask, q_pos >= k_pos)
        return _fold_block(q, k_blk, v_blk, mask, carry)

    if causal:
        # Blocks fully in the future contribute nothing; bound the walk at the
        # last block that can contain key ≤ the block's max query position.
        last = jnp.minimum(
            (q_block_idx * block_q + block_q + block_k - 1) // block_k,
            num_kv_blocks,
        )
    else:
        last = num_kv_blocks
    m_acc, l_acc, acc = jax.lax.fori_loop(
        0, last, body, _init_carry(block_q, head_dim)
    )

    l_safe = jnp.where(l_acc == 0.0, 1.0, l_acc)
    o_ref[:] = (acc / l_safe).astype(o_ref.dtype)


def _fold_heads(x: jax.Array, b: int, h: int, d: int) -> jax.Array:
    """[B, S, H, D] -> [B*H, S, D]."""
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], d)


def _out_struct(shape, dtype, *arrays: jax.Array) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct for a pallas_call out_shape, carrying the union of
    the inputs' varying-mesh-axes where this JAX tracks them: under shard_map
    (where vma checking applies) the out_shape must state how the output
    varies; it varies wherever any input does. Legacy JAX (no `jax.typeof`,
    no `vma=` kwarg) validates with check_rep instead and needs neither."""
    if not hasattr(jax, "typeof"):
        return jax.ShapeDtypeStruct(shape, dtype)
    vma: frozenset = frozenset()
    for a in arrays:
        vma = vma | getattr(jax.typeof(a), "vma", frozenset())
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma)


def _pad_axis(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Fused online-softmax attention. [B, S, H, D] -> [B, S, H, D].

    Self-attention shapes only (q and k share a sequence length). `interpret`
    runs the Pallas interpreter (slow; for tests/debugging off-TPU).
    """
    b, s, h, d = q.shape
    scale = d**-0.5
    fold = functools.partial(_fold_heads, b=b, h=h, d=d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    qf = _pad_axis(qf, 1, block_q)
    kf = _pad_axis(kf, 1, block_k)
    vf = _pad_axis(vf, 1, block_k)
    s_q_pad, s_kv_pad = qf.shape[1], kf.shape[1]

    kernel = functools.partial(
        _flash_kernel, scale=scale, block_k=block_k, causal=causal, kv_len=s
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, s_q_pad // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s_kv_pad, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s_kv_pad, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=_out_struct((b * h, s_q_pad, d), q.dtype, qf, kf, vf),
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :s]  # strip query padding
    return jnp.transpose(out.reshape(b, h, s, d), (0, 2, 1, 3))


def best_attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False):
    """Backend dispatch: the Pallas kernel on TPU, pure-JAX elsewhere."""
    if jax.default_backend() == "tpu":
        return flash_attention(q, k, v, causal=causal)
    return full_attention(q, k, v, causal=causal)


def _flash_chunk_kernel(
    q_ref, k_ref, v_ref, qpos_ref, kpos_ref, o_ref, m_ref, l_ref,
    *, scale: float, block_k: int, causal: bool
):
    """One K/V chunk's UNNORMALIZED contribution + online-softmax stats.

    Like `_flash_kernel` but (a) query/key positions come from refs (the
    caller supplies GLOBAL positions, so a ring-attention shard can attend a
    rotated K/V block correctly) and (b) the outputs are the raw streaming
    accumulator (acc, m, l) so the caller can fold several chunks — this is
    exactly ring attention's per-block contract."""
    block_q, head_dim = q_ref.shape
    s_kv = k_ref.shape[0]
    num_kv_blocks = s_kv // block_k

    q = q_ref[:].astype(jnp.float32) * scale
    q_pos = qpos_ref[:].reshape(block_q, 1)  # [Bq, 1] int32 global positions

    def body(j, carry):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        if causal:
            k_pos = kpos_ref[pl.ds(j * block_k, block_k), :].reshape(1, block_k)
            mask = q_pos >= k_pos
        else:
            mask = None
        return _fold_block(q, k_blk, v_blk, mask, carry)

    if causal:
        # Positions are contiguous ascending within a ring chunk; key blocks
        # entirely in this query block's future contribute nothing — bound
        # the walk (blocks whose first key position <= the max query pos).
        max_q = qpos_ref[block_q - 1, 0]
        k0 = kpos_ref[0, 0]
        last = jnp.clip((max_q - k0) // block_k + 1, 0, num_kv_blocks)
    else:
        last = num_kv_blocks
    m_acc, l_acc, acc = jax.lax.fori_loop(
        0, last, body, _init_carry(block_q, head_dim)
    )
    o_ref[:] = acc
    # Fully-masked rows keep m = -inf internally; emit a finite proxy (their
    # l and acc are 0, so the caller's accumulator fold stays NaN-free) —
    # same guard as the pure-JAX _block_attend.
    m_ref[:] = jnp.where(jnp.isfinite(m_acc), m_acc, 0.0)
    l_ref[:] = l_acc


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention_chunk(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_positions: jax.Array,
    k_positions: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
):
    """Per-chunk streaming attention for ring composition.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; q_positions [Sq] / k_positions [Sk]
    are GLOBAL sequence positions (int32) for causal masking across rotated
    blocks. Requires Sq % block_q == 0 and Sk % block_k == 0 (ring shards
    are uniformly sized). Returns (pv [B, Sq, H, D] unnormalized fp32,
    m [B, H, Sq] fp32 running max, l [B, H, Sq] fp32 normalizer) — the exact
    contract of ring attention's per-block accumulator fold.
    """
    b, s_q, h, d = q.shape
    s_kv = k.shape[1]
    if s_q % block_q or s_kv % block_k:
        raise ValueError(
            f"block sizes must divide the chunk lengths: got Sq={s_q} vs "
            f"block_q={block_q}, Sk={s_kv} vs block_k={block_k}"
        )
    scale = d**-0.5
    fold = functools.partial(_fold_heads, b=b, h=h, d=d)
    qf, kf, vf = fold(q), fold(k), fold(v)
    qpos = q_positions.astype(jnp.int32).reshape(s_q, 1)
    kpos = k_positions.astype(jnp.int32).reshape(s_kv, 1)

    kernel = functools.partial(
        _flash_chunk_kernel, scale=scale, block_k=block_k, causal=causal
    )
    pv, m, l = pl.pallas_call(
        kernel,
        grid=(b * h, s_q // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, s_kv, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, s_kv, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((block_q, 1), lambda i, j: (j, 0)),
            pl.BlockSpec((s_kv, 1), lambda i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            _out_struct((b * h, s_q, d), jnp.float32, qf, kf, vf, qpos, kpos),
            _out_struct((b * h, s_q, 1), jnp.float32, qf, kf, vf, qpos, kpos),
            _out_struct((b * h, s_q, 1), jnp.float32, qf, kf, vf, qpos, kpos),
        ],
        interpret=interpret,
    )(qf, kf, vf, qpos, kpos)

    pv = jnp.transpose(pv.reshape(b, h, s_q, d), (0, 2, 1, 3))  # [B, Sq, H, D]
    m = m.reshape(b, h, s_q)
    l = l.reshape(b, h, s_q)
    return pv, m, l
