"""Welford running mean/std for observation normalization, mesh-aware.

Equivalent of the reference's Acme-derived stoix/utils/running_statistics.py
(559 LoC) with the pmap-era `_psum_over_axes` (reference
running_statistics.py:62-70) redesigned for the mesh world: `update` takes
`axis_names` and psums counts/sums over those mesh axes, so it works identically
under `shard_map` (axis names = mesh axes) and under plain single-shard jit
(axis_names=()).

Unlike the reference, there is no dynamic NamedTuple field injection
(`add_field_to_state`, reference :444): systems that normalize observations
declare the statistics field in their learner-state type explicitly — simpler
and fully typed.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp

Array = jax.Array


class RunningStatisticsState(NamedTuple):
    count: Array  # scalar fp32 — total elements folded in (global)
    mean: Any  # pytree like the observation
    summed_variance: Any
    std: Any


def init_state(template: Any) -> RunningStatisticsState:
    """Build zeroed statistics shaped like `template` (a dummy observation)."""
    zeros = jax.tree.map(lambda x: jnp.zeros(jnp.shape(x), jnp.float32), template)
    ones = jax.tree.map(lambda x: jnp.ones(jnp.shape(x), jnp.float32), template)
    return RunningStatisticsState(
        count=jnp.zeros((), jnp.float32), mean=zeros, summed_variance=zeros, std=ones
    )


def _all_sum(x: Array, axis_names: Sequence[str]) -> Array:
    for name in axis_names:
        x = jax.lax.psum(x, axis_name=name)
    return x


def update(
    state: RunningStatisticsState,
    batch: Any,
    *,
    axis_names: Sequence[str] = (),
    std_min_value: float = 1e-6,
    std_max_value: float = 1e6,
) -> RunningStatisticsState:
    """Fold a batch of observations into the running statistics.

    `batch` leaves have shape [leading..., *feature_shape] where feature_shape
    matches the statistics leaves; all leading axes are reduced. When called
    inside shard_map/vmap with named axes, pass them via `axis_names` to get
    cross-device-consistent statistics (each shard folds its local batch, psum
    makes the result global).
    """
    mean_leaves, treedef = jax.tree.flatten(state.mean)
    batch_leaves = treedef.flatten_up_to(batch)

    # All leaves share the same leading batch shape; count it once.
    feat_ndim = mean_leaves[0].ndim
    lead_shape = batch_leaves[0].shape[: batch_leaves[0].ndim - feat_ndim]
    local_count = jnp.prod(jnp.asarray(lead_shape, jnp.float32)) if lead_shape else jnp.asarray(1.0)
    batch_count = _all_sum(local_count, axis_names)
    new_count = state.count + batch_count

    new_means, new_vars, new_stds = [], [], []
    for mean, svar, b in zip(mean_leaves, jax.tree.leaves(state.summed_variance), batch_leaves):
        reduce_axes = tuple(range(b.ndim - mean.ndim))
        diff_sum = _all_sum(jnp.sum(b - mean, axis=reduce_axes), axis_names)
        new_mean = mean + diff_sum / new_count
        diff2_sum = _all_sum(jnp.sum((b - mean) * (b - new_mean), axis=reduce_axes), axis_names)
        new_svar = svar + diff2_sum
        new_std = jnp.clip(jnp.sqrt(new_svar / new_count), std_min_value, std_max_value)
        new_means.append(new_mean)
        new_vars.append(new_svar)
        new_stds.append(new_std)

    return RunningStatisticsState(
        count=new_count,
        mean=treedef.unflatten(new_means),
        summed_variance=treedef.unflatten(new_vars),
        std=treedef.unflatten(new_stds),
    )


def normalize(batch: Any, state: RunningStatisticsState, max_abs_value: float | None = None) -> Any:
    def _norm(b: Array, mean: Array, std: Array) -> Array:
        out = (b - mean) / std
        if max_abs_value is not None:
            out = jnp.clip(out, -max_abs_value, max_abs_value)
        return out

    return jax.tree.map(_norm, batch, state.mean, state.std)


def denormalize(batch: Any, state: RunningStatisticsState) -> Any:
    return jax.tree.map(lambda b, mean, std: b * std + mean, batch, state.mean, state.std)


def clip(batch: Any, max_abs_value: float) -> Any:
    return jax.tree.map(lambda b: jnp.clip(b, -max_abs_value, max_abs_value), batch)


def normalize_observation(
    observation: Any, state: RunningStatisticsState, max_abs_value: float = 10.0
) -> Any:
    """Normalize an Observation struct's agent_view in place of per-call-site
    _replace idioms (one definition so actor/learner/eval paths cannot drift)."""
    return observation._replace(
        agent_view=normalize(observation.agent_view, state, max_abs_value=max_abs_value)
    )
