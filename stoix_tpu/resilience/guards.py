"""In-jit divergence guards for the gradient step (docs/DESIGN.md §2.3).

A single non-finite gradient silently poisons params forever: NaN propagates
through optax's update into every weight, and every later loss is NaN while
the run keeps "training". The guard wraps the minibatch update of the
PPO/IMPALA/DQN-family systems with non-finite detection on the LOSS and the
GLOBAL GRAD-NORM, selected by `system.update_guard`:

  off    (default) bit-identical: the guard adds ZERO ops and no metrics
  skip   `jnp.where` the whole (params, opt_states) update to a no-op when
         the signal is non-finite; the optimizer step-count still advances
         (a skipped batch is a consumed batch — bias-correction schedules
         keep moving); a `skipped_updates` flag rides the train metrics and
         the host sums it into the `stoix_tpu_learner_skipped_updates_total`
         counter
  halt   same in-jit selection (params stay finite for the emergency
         checkpoint), plus the host raises DivergenceError naming the step,
         the loss, and the offending metric as soon as the window's metrics
         are materialized

Cross-replica consistency: params are REPLICATED over every axis their
gradients are pmean'ed over — the mesh "data" axis always, and the in-shard
`vmap("batch")` update-batch axis in the Anakin systems (grads sync over
both, so the [U] replicas stay bit-identical and `unbatch_params` may take
replica 0). Every replica must therefore make the SAME keep/skip decision:
the detection loss is `lax.pmean`ed over `axis_names` — which must match the
system's gradient-sync axes — before the finiteness test (NaN anywhere
pmean-propagates everywhere); the grad-norm is computed from the
already-pmeaned gradients the caller passes, identical per replica by
construction. Axes in `metric_axes` (the vmap subset of `axis_names` whose
replicas materialize as entries in the emitted metrics tree) pre-divide the
skipped-update flag by their size so the host-side sum counts each skipped
update exactly once, not once per replica.

Fault injection (`nan_loss:N`, resilience/faultinject.py) lives inside the
guard: at optimizer step-count N the loss AND every floating leaf of the
update are poisoned with NaN — under `off` this demonstrably NaNs the params
(the failure mode the guard exists for); under `skip`/`halt` the guard must
catch it. The step count is discovered inside the optimizer state (optax's
`ScaleByAdamState.count` etc.) so injection is deterministic with no carry
changes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from stoix_tpu.observability import get_registry
from stoix_tpu.resilience import faultinject
from stoix_tpu.resilience.errors import DivergenceError

VALID_MODES = ("off", "skip", "halt")
SKIPPED_COUNTER = "stoix_tpu_learner_skipped_updates_total"


def resolve_mode(config: Any) -> str:
    """Validated `system.update_guard` ('off' when unset)."""
    raw = config.system.get("update_guard", "off")
    mode = "off" if raw in (None, False, "~") else str(raw).lower()
    if mode not in VALID_MODES:
        raise ValueError(
            f"system.update_guard={raw!r} is not one of {list(VALID_MODES)}"
        )
    return mode


def find_step_count(tree: Any) -> Optional[Any]:
    """First leaf bound to a NamedTuple field named 'count' (optax keeps the
    optimizer step there, e.g. ScaleByAdamState.count). Depth-first through
    NamedTuples/tuples/lists/dicts; None when absent."""
    if hasattr(tree, "_fields"):
        for field in tree._fields:
            value = getattr(tree, field)
            if field == "count" and not hasattr(value, "_fields"):
                return value
            found = find_step_count(value)
            if found is not None:
                return found
    elif isinstance(tree, (tuple, list)):
        for value in tree:
            found = find_step_count(value)
            if found is not None:
                return found
    elif isinstance(tree, dict):
        for value in tree.values():
            found = find_step_count(value)
            if found is not None:
                return found
    return None


def _advance_counts(selected: Any, new: Any) -> Any:
    """Return `selected` with every NamedTuple field named 'count' taken from
    `new`: a skipped update keeps old params/moments but still consumes the
    step (otherwise a fault pinned to step N would re-fire forever because
    the count never passes N)."""
    if hasattr(selected, "_fields"):
        return type(selected)(*(
            getattr(new, f) if f == "count" and not hasattr(getattr(selected, f), "_fields")
            else _advance_counts(getattr(selected, f), getattr(new, f))
            for f in selected._fields
        ))
    if isinstance(selected, tuple):
        return type(selected)(_advance_counts(s, n) for s, n in zip(selected, new))
    if isinstance(selected, list):
        return [_advance_counts(s, n) for s, n in zip(selected, new)]
    if isinstance(selected, dict):
        return {k: _advance_counts(selected[k], new[k]) for k in selected}
    return selected


def guard_update(
    mode: str,
    *,
    new: Any,
    old: Any,
    loss: Any,
    grads: Any,
    opt_state: Any = None,
    axis_names: Sequence[str] = ("data",),
    metric_axes: Sequence[str] = (),
) -> Tuple[Any, Dict[str, Any]]:
    """In-jit guard around one minibatch update.

    `new`/`old` are matching (params, opt_states) pytrees (post/pre update);
    `loss` is the minibatch loss (scalar, may be per-replica — it is
    pmean'ed over `axis_names`, which MUST match the system's gradient-sync
    axes, for a replica-consistent verdict); `grads` must be the SYNCED
    (already pmean'ed) gradients; `opt_state` (the pre-update one) is only
    used to locate the optimizer step-count for deterministic fault
    injection; `metric_axes` are the vmap axes among `axis_names` whose
    replicas appear as separate entries in the emitted metrics (the flag is
    pre-divided by their size so the host sum is an exact count). Returns
    (selected_carry, guard_metrics) — metrics is `{}` under mode 'off' with
    no fault armed, keeping the train-metrics tree (and therefore the whole
    program) bit-identical.
    """
    poison_at = faultinject.poison_step()
    if mode == "off" and poison_at is None:
        return new, {}

    loss = jnp.asarray(loss, jnp.float32)
    if poison_at is not None:
        count = find_step_count(opt_state)
        if count is None:
            poison = jnp.float32(jnp.nan)  # no counter found: poison always
        else:
            poison = jnp.where(
                jnp.asarray(count) == poison_at, jnp.nan, 0.0
            ).astype(jnp.float32)
        loss = loss + poison
        # (poison * 0) is NaN when armed, 0.0 otherwise: adding it to every
        # floating leaf makes the injected fault a REAL poisoned update, not
        # just a poisoned detection signal.
        taint = poison * 0.0
        new = jax.tree.map(
            lambda x: x + taint.astype(x.dtype)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
            else x,
            new,
        )
    if mode == "off":
        return new, {}

    grad_norm = jnp.asarray(optax.global_norm(grads), jnp.float32)
    for axis in axis_names:
        loss = jax.lax.pmean(loss, axis_name=axis)
    bad = jnp.logical_not(jnp.isfinite(loss) & jnp.isfinite(grad_norm))
    selected = jax.tree.map(lambda n, o: jnp.where(bad, o, n), new, old)
    selected = _advance_counts(selected, new)
    flag = bad.astype(jnp.float32)
    for axis in metric_axes:
        # The flag is identical across this vmap axis (the verdict is synced
        # over it) but each replica emits its own metrics entry: pre-divide
        # so the host-side sum counts the skip once, not axis-size times.
        flag = flag / jax.lax.psum(1, axis_name=axis)
    metrics = {
        "skipped_updates": flag,
        "guard_loss": loss,
        "guard_grad_norm": grad_norm,
    }
    return selected, metrics


def skipped_counter():
    return get_registry().counter(
        SKIPPED_COUNTER,
        "Gradient updates no-op'ed by the divergence guard (update_guard=skip/halt)",
    )


def publish_guard_metrics(mode: str, train_metrics: Any, step: int) -> float:
    """Host-side half of the guard, called once per window/update with the
    MATERIALIZED train metrics: folds the window's skipped-update flags into
    the registry counter and, under 'halt', raises DivergenceError at the
    first flagged entry. Returns the number of skips seen this call."""
    if mode == "off":
        return 0.0
    flags = train_metrics.get("skipped_updates") if hasattr(train_metrics, "get") else None
    if flags is None:
        return 0.0
    flags = np.asarray(flags, np.float64).reshape(-1)
    skipped = float(flags.sum())
    if skipped:
        skipped_counter().inc(skipped)
        if mode == "halt":
            losses = np.asarray(train_metrics["guard_loss"], np.float64).reshape(-1)
            norms = np.asarray(train_metrics["guard_grad_norm"], np.float64).reshape(-1)
            idx = int(np.argmax(flags > 0.0))
            metric = "loss" if not np.isfinite(losses[idx]) else "grad_norm"
            raise DivergenceError(step, losses[idx], norms[idx], metric)
    return skipped
