"""Deadline watchdogs for first-compile and first-window execution.

A wedged device runtime does not crash — it absorbs the first XLA compile or
the first program execution and never answers, leaving the host loop blocked
inside a native PJRT call that no Python signal handler can interrupt (signal
handlers only run between bytecodes; round 1's SIGALRM watchdog emitted
nothing for exactly this reason). `Watchdog` is a deadline THREAD around a
named stage (docs/DESIGN.md §2.4):

  * On expiry it first DUMPS the diagnosis — every thread's stack (via
    `sys._current_frames`) plus the observability registry snapshot — to the
    `stoix_tpu.resilience` log, so even a hard-wedged run leaves evidence of
    WHERE every thread was stuck.
  * Then it raises `CompileStallError` in the protected section via
    `_thread.interrupt_main()` — effective whenever the main thread is in
    Python (a slow compile loop, an injected `slow_compile` fault, a blocked
    queue wait).
  * A main thread wedged inside native code cannot be interrupted; when
    `hard_exit_grace_s > 0`, a second timer `os._exit(EXIT_CODE_STALL)`s
    after that grace so the job FAILS (and the scheduler retries) instead of
    burning its whole time limit. 0 disables the hard exit (the default:
    library code should not own process death unless asked).

Stage begin/end beat the shared `HeartbeatBoard` (component
`host-<stage>`), so the registry snapshot taken during a stall — by this
watchdog or by an operator scraping metrics — shows how long ago the host
loop last made progress, with the same vocabulary Sebulba health uses.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from stoix_tpu.observability import HeartbeatBoard, flightrec, get_logger, get_registry
from stoix_tpu.resilience.errors import CompileStallError

# Exit code for the hard-exit path: distinct from Python's 1 and SIGKILL's
# 137 so schedulers/wrappers can tell "watchdog shot a wedged run" apart.
# Declared in the canonical registry (resilience/exit_codes.py, STX018);
# re-exported here because this module has owned the name since PR 4.
from stoix_tpu.resilience.exit_codes import EXIT_CODE_STALL

_board_lock = threading.Lock()
_board: Optional[HeartbeatBoard] = None


def get_watchdog_board() -> HeartbeatBoard:
    """Process-wide board the watchdogs beat (lazy: a HeartbeatBoard registers
    metrics, which must not happen at import time)."""
    global _board
    with _board_lock:
        if _board is None:
            _board = HeartbeatBoard()
        return _board


def dump_thread_stacks() -> str:
    """Every live thread's current stack, named — the core of the stall dump.
    Pure-Python introspection: safe to call from the watchdog thread while the
    main thread is blocked in native code (its last Python frame still shows
    WHICH native call it entered)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, "unknown")
        stack = "".join(traceback.format_stack(frame))
        chunks.append(f"--- thread {name} (ident {ident}) ---\n{stack}")
    return "\n".join(chunks)


def dump_state(stage: str) -> str:
    """Thread stacks + registry snapshot: everything a post-mortem needs from
    a wedged process, as one log-friendly string."""
    get_watchdog_board().export_ages()
    try:
        snapshot = json.dumps(get_registry().snapshot(), default=str, indent=2)
    except Exception as exc:  # noqa: BLE001 — a broken snapshot must not lose the stacks
        snapshot = f"<registry snapshot failed: {type(exc).__name__}: {exc}>"
    return (
        f"===== watchdog stall dump: stage '{stage}' =====\n"
        f"{dump_thread_stacks()}\n"
        f"===== metrics registry snapshot =====\n{snapshot}"
    )


class Watchdog:
    """Deadline thread around one named stage; use as a context manager.

        with Watchdog("first_compile", deadline_s=1800):
            learn = aot_warmup(learn, state)

    On deadline expiry: dump (stacks + registry) -> interrupt the main thread
    -> raise CompileStallError from __exit__. With `hard_exit_grace_s > 0`, a
    main thread still wedged in native code that long after the dump gets
    `os._exit(EXIT_CODE_STALL)` — no hang survives."""

    def __init__(
        self,
        stage: str,
        deadline_s: float,
        hard_exit_grace_s: float = 0.0,
        board: Optional[HeartbeatBoard] = None,
        error_factory: Optional[Callable[[str, float, Optional[str]], BaseException]] = None,
        exit_code: int = EXIT_CODE_STALL,
    ):
        self.stage = stage
        self.deadline_s = float(deadline_s)
        self.hard_exit_grace_s = float(hard_exit_grace_s)
        # The stall error to raise on expiry: (stage, deadline_s, dump) ->
        # exception. Defaults to CompileStallError (the launch-hardening
        # stages); fleet barriers (resilience/fleet.py) substitute
        # FleetBarrierTimeout and the fleet exit code so the SAME deadline
        # machinery serves both failure vocabularies.
        self._error_factory = error_factory or (
            lambda stage, deadline, dump: CompileStallError(stage, deadline, dump=dump)
        )
        self._exit_code = int(exit_code)
        self._board = board
        self._component = f"host-{stage}"
        self._timer: Optional[threading.Timer] = None
        self._hard_timer: Optional[threading.Timer] = None
        self._done = threading.Event()
        self.stalled = False
        self.dump: Optional[str] = None

    # -- watchdog-thread side -------------------------------------------------
    def _on_deadline(self) -> None:
        if self._done.is_set():
            return
        dump = dump_state(self.stage)
        # Re-check AFTER the (non-trivial) dump: if the protected section
        # completed while we were formatting stacks, interrupting now would
        # land a stray KeyboardInterrupt in whatever the host loop runs next
        # — a healthy run killed by its own watchdog. The remaining window
        # (between this check and interrupt delivery) is unavoidable; __exit__
        # converts any stalled-flagged exception, so only a post-__exit__
        # delivery could leak, and that requires the section to finish in
        # exactly these few instructions.
        if self._done.is_set():
            return
        self.stalled = True
        self.dump = dump
        log = get_logger("stoix_tpu.resilience")
        log.error(
            "[watchdog] stage '%s' exceeded its %.0fs deadline — dumping all "
            "thread stacks and interrupting the main thread\n%s",
            self.stage, self.deadline_s, self.dump,
        )
        get_registry().counter(
            "stoix_tpu_watchdog_stalls_total",
            "Watchdog deadlines blown, by stage",
        ).inc(labels={"stage": self.stage})
        flightrec.get_flight_recorder().record(
            "watchdog_stall", stage=self.stage, deadline_s=self.deadline_s
        )
        if self.hard_exit_grace_s > 0:
            self._hard_timer = threading.Timer(self.hard_exit_grace_s, self._hard_exit)
            self._hard_timer.daemon = True
            self._hard_timer.start()
        import _thread

        _thread.interrupt_main()

    def _hard_exit(self) -> None:
        if self._done.is_set():
            return
        get_logger("stoix_tpu.resilience").error(
            "[watchdog] main thread still wedged %.0fs after the '%s' stall "
            "dump (native call uninterruptible) — hard exit %d",
            self.hard_exit_grace_s, self.stage, self._exit_code,
        )
        # The rc-86 flight record: dumped from the watchdog thread because
        # os._exit skips atexit/finally — this is the last Python that runs.
        flightrec.dump_flight_record(
            None,
            reason=f"watchdog stall in stage '{self.stage}'",
            exit_code=self._exit_code,
        )
        # Flush what we can: logging handlers buffer, and this process is done.
        sys.stderr.flush()
        os._exit(self._exit_code)

    # -- protected-section side ----------------------------------------------
    def __enter__(self) -> "Watchdog":
        board = self._board or get_watchdog_board()
        board.beat(self._component)
        self._started_at = time.monotonic()
        self._timer = threading.Timer(self.deadline_s, self._on_deadline)
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self._done.set()
        if self._timer is not None:
            self._timer.cancel()
        if self._hard_timer is not None:
            self._hard_timer.cancel()
        (self._board or get_watchdog_board()).beat(self._component)
        if self.stalled:
            # The KeyboardInterrupt interrupt_main() raised (when it landed —
            # the section may also have completed in the race window) is the
            # watchdog's own mechanism, not an operator ^C: convert it.
            raise self._error_factory(self.stage, self.deadline_s, self.dump) from exc
        return False
