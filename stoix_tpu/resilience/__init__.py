"""Fault tolerance for both Podracer architectures (docs/DESIGN.md §2.3).

Zero-dependency, off-by-default-transparent. Four pillars:

  * **Divergence guards** (guards.py): `system.update_guard=off|skip|halt`
    wraps the gradient step of the PPO/IMPALA/DQN-family systems with
    non-finite detection on loss + global grad-norm; `skip` no-ops bad
    updates (counter: `stoix_tpu_learner_skipped_updates`), `halt` raises
    DivergenceError on the host naming step/loss/metric.
  * **Preemption-safe stop/resume** (preemption.py): SIGTERM/SIGINT request a
    graceful stop at the next window boundary; the Anakin runner drains its
    pipelined dispatcher, writes an emergency checkpoint, and exits cleanly.
    Restore (utils/checkpointing.py) validates integrity and falls back to
    the newest VALID checkpoint when the latest is corrupt.
  * **Sebulba supervision** (supervisor.py): crashed actors restart with
    bounded exponential backoff; unrecoverable/wedged actors propagate a
    typed ComponentFailure poison-pill so the learner fails fast instead of
    burning the collect timeout.
  * **Fault injection** (faultinject.py): `STOIX_TPU_FAULT=actor_crash:3,...`
    deterministically injects crashes, wedges, NaN losses, checkpoint
    corruption, and SIGTERM so tests/test_resilience.py proves every
    recovery path end-to-end.

With everything at defaults (`update_guard=off`, no faults armed, no crashes)
training is bit-identical to a build without this package — guards add zero
ops, the signal handler only reacts to signals, and supervision only acts on
failures (tests/test_resilience.py pins the trajectory equality).
"""

from stoix_tpu.resilience import faultinject, guards  # noqa: F401 — public API
from stoix_tpu.resilience.errors import (  # noqa: F401
    CheckpointIntegrityError,
    ComponentFailure,
    DivergenceError,
    EvaluatorStallError,
    InjectedFault,
)
from stoix_tpu.resilience.preemption import PreemptionHandler  # noqa: F401
from stoix_tpu.resilience.supervisor import (  # noqa: F401
    ActorSupervisor,
    supervisor_from_config,
)
