"""Fault tolerance for both Podracer architectures (docs/DESIGN.md §2.3).

Zero-dependency, off-by-default-transparent. Four pillars:

  * **Divergence guards** (guards.py): `system.update_guard=off|skip|halt`
    wraps the gradient step of the PPO/IMPALA/DQN-family systems with
    non-finite detection on loss + global grad-norm; `skip` no-ops bad
    updates (counter: `stoix_tpu_learner_skipped_updates_total`), `halt` raises
    DivergenceError on the host naming step/loss/metric.
  * **Preemption-safe stop/resume** (preemption.py): SIGTERM/SIGINT request a
    graceful stop at the next window boundary; the Anakin runner drains its
    pipelined dispatcher, writes an emergency checkpoint, and exits cleanly.
    Restore (utils/checkpointing.py) validates integrity and falls back to
    the newest VALID checkpoint when the latest is corrupt.
  * **Sebulba supervision** (supervisor.py): crashed actors restart with
    bounded exponential backoff; unrecoverable/wedged actors propagate a
    typed ComponentFailure poison-pill so the learner fails fast instead of
    burning the collect timeout.
  * **Fault injection** (faultinject.py): `STOIX_TPU_FAULT=actor_crash:3,...`
    deterministically injects crashes, wedges, NaN losses, checkpoint
    corruption, SIGTERM, probe wedges, and slow compiles so
    tests/test_resilience.py proves every recovery path end-to-end.
  * **Launch hardening** (preflight.py / watchdog.py, docs/DESIGN.md §2.4):
    subprocess-isolated backend probe with bounded timeout + backoff retries
    (`BackendUnavailableError` instead of a wedged parent), config
    cross-validation before any device work (`ConfigValidationError` listing
    every finding), AOT `memory_analysis()` vs device HBM
    (`ResourcePreflightError` in seconds, not a runtime OOM), and deadline
    watchdogs around first-compile/first-window that dump all thread stacks
    + the registry snapshot and raise `CompileStallError` instead of
    hanging. Opt-in via `arch.preflight`; off = bit-identical.
  * **Fleet coordination** (fleet.py, docs/DESIGN.md §2.6): cross-host
    agreement for multi-host SPMD runs — per-host preemption/fault flags
    combined at each window boundary so ALL hosts drain and checkpoint at
    the SAME window; a KV-store heartbeat + monitor that converts a dead
    peer into a typed `FleetPartitionError`, a local-shard emergency
    checkpoint, and exit code 87 (`EXIT_CODE_FLEET_PARTITION`) for the
    launcher's elastic-relaunch supervision; per-host window wall-time skew
    telemetry (`stoix_tpu_fleet_*`); and deadline-guarded barriers. Opt-in
    via `arch.fleet`; off = bit-identical.
  * **State-integrity sentinel** (integrity.py, docs/DESIGN.md §2.9): in-jit
    per-device replica fingerprints riding the coalesced metric fetch prove
    the post-pmean bit-identity invariant every window — a finite-but-wrong
    HBM bit-flip raises a typed `StateCorruptionError` naming the deviating
    device(s) instead of training silently to garbage; an optional
    determinism probe replays a recorded learn step and compares bitwise
    (wrong-math cores at replica count 1); per-leaf sha256 digest manifests
    ride every orbax save and are verified on restore (bit-rot is rejected,
    not resumed); exit code 88 + a quarantine file drive
    `launcher.py --supervise`'s restore-and-quarantine relaunch. Opt-in via
    `arch.integrity`; off = bit-identical.

With everything at defaults (`update_guard=off`, no faults armed, no crashes)
training is bit-identical to a build without this package — guards add zero
ops, the signal handler only reacts to signals, and supervision only acts on
failures (tests/test_resilience.py pins the trajectory equality).
"""

from stoix_tpu.resilience import elastic, exit_codes, faultinject, fleet, guards, integrity, preflight  # noqa: F401 — public API
from stoix_tpu.resilience.exit_codes import (  # noqa: F401
    EXIT_CODE_FAILURE,
    EXIT_CODE_OK,
    EXIT_CODE_STALL,
    EXIT_CODE_USAGE,
)
from stoix_tpu.resilience.errors import (  # noqa: F401
    BackendUnavailableError,
    CheckpointIntegrityError,
    CompileStallError,
    ComponentFailure,
    ConfigValidationError,
    DivergenceError,
    EvaluatorStallError,
    FleetBarrierTimeout,
    FleetError,
    FleetPartitionError,
    InjectedFault,
    PreflightError,
    ResourcePreflightError,
    StateCorruptionError,
)
from stoix_tpu.resilience.fleet import (  # noqa: F401
    EXIT_CODE_FLEET_PARTITION,
    FakeFleetStore,
    FleetCoordinator,
    FleetStragglerWarning,
    fleet_from_config,
)
from stoix_tpu.resilience.integrity import (  # noqa: F401
    EXIT_CODE_STATE_CORRUPTION,
    StateIntegritySentinel,
    sentinel_from_config,
)
from stoix_tpu.resilience.preemption import PreemptionHandler  # noqa: F401
from stoix_tpu.resilience.supervisor import (  # noqa: F401
    ActorSupervisor,
    supervisor_from_config,
)
from stoix_tpu.resilience.watchdog import Watchdog  # noqa: F401
