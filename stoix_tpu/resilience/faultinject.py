"""Deterministic fault injection (chaos layer) for the resilience test-suite.

Armed via the `STOIX_TPU_FAULT` env var or `arch.fault_spec` config key, e.g.

    STOIX_TPU_FAULT=actor_crash:3,nan_loss:50,ckpt_corrupt,sigterm:2

Spec grammar: comma-separated `name[:arg]` entries (a mapping
`{actor_crash: 3, ...}` is accepted from config overrides, where YAML parses
`key:value` into a dict). Faults and their deterministic trigger points:

  actor_crash:N   actor 0 raises InjectedFault at the top of rollout N
                  (one-shot: a supervised replacement does NOT re-crash)
  queue_stall:N   actor 0 wedges (sleeps, still alive) at the top of
                  rollout N — exercises heartbeat wedge detection, which a
                  crash cannot
  nan_loss:N      the in-jit divergence guard poisons the loss AND the
                  parameter update with NaN at optimizer step-count N
                  (resilience/guards.py reads `poison_step()` at trace time)
  ckpt_corrupt    the next Checkpointer.save() waits for serialization and
                  then overwrites the saved step's files with garbage
                  (one-shot) — exercises restore fallback
  sigterm:N       the host loop delivers SIGTERM to its own process after
                  dispatching eval window N (one-shot) — exercises the
                  preemption handler end-to-end, signal delivery included
  backend_wedge   the preflight probe SUBPROCESS (resilience/preflight.py)
                  sleeps forever before touching jax — a PJRT runtime that
                  accepts the process and never answers. Honored in the child
                  (it inherits STOIX_TPU_FAULT), so EVERY probe attempt
                  wedges and the parent's timeout/retry/backoff path runs to
                  BackendUnavailableError deterministically
  slow_compile:S  the host loop sleeps S seconds inside the watchdog-guarded
                  first-compile stage (one-shot) — drives the
                  CompileStallError path without needing a wedged backend
  host_loss:N     this PROCESS freezes (SIGSTOP to itself — every thread
                  including the fleet heartbeat publisher halts, sockets
                  stay OPEN) right after dispatching eval window N: a host
                  lost to a hung VM, a network partition, or a preemption
                  freeze. This is the silent case jax's own coordination
                  service cannot see (a crashed host that CLOSES its sockets
                  is already fatal-error-propagated and aborted by jax
                  itself); only fleet heartbeats catch it. Armed on one
                  process of a multi-host run it drives the surviving peers'
                  monitor to FleetPartitionError + the local-shard emergency
                  checkpoint (resilience/fleet.py, docs/DESIGN.md §2.6). If
                  something SIGCONTs the frozen process it hard-exits with
                  EXIT_CODE_FAILURE — the host stays lost.
  host_stall:S    this process sleeps S seconds at the top of eval window 1
                  (one-shot) — a straggler host, alive but slow. Exercises
                  the fleet skew telemetry (stoix_tpu_fleet_* gauges +
                  FleetStragglerWarning) and heartbeat near-staleness, which
                  host_loss cannot
  barrier_wedge   fleet.guarded_barrier sleeps forever INSTEAD of arriving at
                  the barrier — a peer that never shows up — so the barrier
                  deadline watchdog's FleetBarrierTimeout path runs
                  deterministically without a real dead host
  bitflip:N       one mantissa bit of ONE replica's params is flipped going
                  into eval window N (one-shot): the replicated learner
                  state is reassembled with the lowest-id local device's
                  copy differing by one ulp — a simulated HBM bit-flip.
                  Finite, silent, and exactly the class only the integrity
                  sentinel's replica fingerprints can see
                  (resilience/integrity.py, docs/DESIGN.md §2.9). On a
                  multi-process run only process 0 flips its device.
  swap_poison     the serving hot-swap watcher's NEXT loaded candidate gets
                  a NaN written into its first float leaf (one-shot) —
                  drives the hot-swap canary's reject-and-keep-serving path
                  (serve/hotswap.py) deterministically
  shrink:N        after dispatching eval window N the run vacates for a
                  SMALLER topology (one-shot): emergency snapshot, a
                  `resize_request.json` naming half the current device
                  count, schema-valid flight record, hard exit 89
                  (resilience/elastic.py, docs/DESIGN.md §2.14) — a
                  preemption that takes half the allocation. The elastic
                  supervisor relaunches at the requested count.
  grow:N          same protocol, but the resize request names DOUBLE the
                  current device count (one-shot) — preempted capacity
                  coming back. The elastic supervisor relaunches larger,
                  restoring from the newest digest-verified store.
  replica_kill:N  the closed-loop runner hard-closes serve-fleet replica N
                  mid-traffic (one-shot): in-flight requests on that replica
                  complete with ServerClosedError and the FleetRouter must
                  fail them over to a surviving replica with zero silent
                  drops (stoix_tpu/loop, docs/DESIGN.md §2.15). The runner
                  restarts the replica after the router's re-admission
                  cooldown — the self-healing path.
  replica_slow:S  serve-fleet replica 0's batch worker sleeps S milliseconds
                  before EVERY batch (sustained, counted once) — a straggler
                  replica, alive and answering but slow. Drives the router's
                  tail-latency/hedging surface, which a kill cannot.
  feedback_stall:S the experience recorder's replay feeder thread wedges S
                  seconds (one-shot, sliced sleep) — a stalled replay
                  ingest. The recorder's bounded queue must absorb it by
                  dropping oldest (counted), never by blocking the serve
                  path.

All injection points are no-ops (a single None check) when no plan is armed,
and `configure()` is called once per experiment so one-shot state never leaks
across runs in the same process. This module is the ONLY place in stoix_tpu/
allowed to swallow broad exceptions (lint STX003 allowlist): a broken chaos
layer must never mask the failure it was injecting.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from stoix_tpu.observability import flightrec, get_logger, get_registry, goodput
from stoix_tpu.resilience.errors import InjectedFault
from stoix_tpu.resilience.exit_codes import EXIT_CODE_FAILURE

ENV_VAR = "STOIX_TPU_FAULT"

_KNOWN = (
    "actor_crash",
    "queue_stall",
    "nan_loss",
    "ckpt_corrupt",
    "sigterm",
    "backend_wedge",
    "slow_compile",
    "host_loss",
    "host_stall",
    "barrier_wedge",
    "bitflip",
    "swap_poison",
    "shrink",
    "grow",
    "replica_kill",
    "replica_slow",
    "feedback_stall",
)


class FaultPlan:
    """Parsed fault spec plus one-shot consumption state (thread-safe)."""

    def __init__(self, faults: Dict[str, Optional[int]]):
        unknown = set(faults) - set(_KNOWN)
        if unknown:
            raise ValueError(
                f"unknown fault(s) {sorted(unknown)}; known: {list(_KNOWN)}"
            )
        self.faults = dict(faults)
        self._lock = threading.Lock()
        self._consumed: set = set()

    def arg(self, name: str) -> Optional[int]:
        """The fault's trigger argument, or None when the fault is not armed.
        `ckpt_corrupt` is armed with arg 0 (no argument needed)."""
        if name not in self.faults:
            return None
        value = self.faults[name]
        return 0 if value is None else int(value)

    def consume(self, name: str) -> bool:
        """One-shot gate: True exactly once per armed fault per plan."""
        with self._lock:
            if name not in self.faults or name in self._consumed:
                return False
            self._consumed.add(name)
            return True

    def __repr__(self) -> str:
        return f"FaultPlan({self.faults})"


def parse_spec(spec: Any) -> Optional[FaultPlan]:
    """Parse a spec string (`name:arg,name`) or mapping into a FaultPlan;
    None/empty means no faults."""
    if not spec:
        return None
    if isinstance(spec, dict):
        return FaultPlan({str(k): (None if v is None else int(v)) for k, v in spec.items()})
    faults: Dict[str, Optional[int]] = {}
    for entry in str(spec).split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, arg = entry.partition(":")
        faults[name.strip()] = int(arg) if arg else None
    return FaultPlan(faults) if faults else None


_lock = threading.Lock()
_plan: Optional[FaultPlan] = None


def configure(config_spec: Any = None) -> Optional[FaultPlan]:
    """Install the process-wide plan for one experiment run. The env var wins
    over the config spec so an operator can chaos-test any entry point without
    editing configs. Resets one-shot state; call at run start."""
    global _plan
    spec = os.environ.get(ENV_VAR) or config_spec
    with _lock:
        _plan = parse_spec(spec)
        if _plan is not None:
            get_logger("stoix_tpu.resilience").warning(
                "[faultinject] CHAOS ACTIVE: %s", _plan
            )
    return _plan


def get_plan() -> Optional[FaultPlan]:
    with _lock:
        return _plan


def reset() -> None:
    global _plan
    with _lock:
        _plan = None


def _injected_counter():
    return get_registry().counter(
        "stoix_tpu_resilience_faults_injected_total",
        "Faults fired by the injection harness, by fault name",
    )


def poison_step() -> Optional[int]:
    """Optimizer step-count at which the guard should poison the loss/update,
    or None. Read at TRACE time by resilience/guards.py — `configure()` must
    run before the learner is built (both runners do)."""
    plan = get_plan()
    return None if plan is None else plan.arg("nan_loss")


def maybe_crash_actor(actor_id: int, rollout_idx: int) -> None:
    """Raise InjectedFault when `actor_crash:N` is armed, actor 0, rollout N.
    One-shot: the supervised replacement thread does not re-crash."""
    plan = get_plan()
    if plan is None or actor_id != 0:
        return
    at = plan.arg("actor_crash")
    if at is not None and rollout_idx == at and plan.consume("actor_crash"):
        _injected_counter().inc(labels={"fault": "actor_crash"})
        raise InjectedFault(
            f"injected actor crash (actor-{actor_id}, rollout {rollout_idx})"
        )


def maybe_stall_queue(
    actor_id: int,
    rollout_idx: int,
    should_abort: Optional[Callable[[], bool]] = None,
    max_stall_s: float = 600.0,
) -> None:
    """Wedge (sleep, thread stays alive) when `queue_stall:N` is armed, actor
    0, rollout N — the silent-stall failure mode heartbeat wedge detection
    exists for. Aborts early when `should_abort()` turns true (shutdown)."""
    plan = get_plan()
    if plan is None or actor_id != 0:
        return
    at = plan.arg("queue_stall")
    if at is None or rollout_idx != at or not plan.consume("queue_stall"):
        return
    _injected_counter().inc(labels={"fault": "queue_stall"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] actor-%d wedged at rollout %d", actor_id, rollout_idx
    )
    flightrec.get_flight_recorder().record(
        "fault", fault="queue_stall", actor=actor_id, rollout=rollout_idx
    )
    wedge_started = time.monotonic()
    deadline = time.monotonic() + max_stall_s
    try:
        while time.monotonic() < deadline:
            if should_abort is not None and should_abort():
                return
            time.sleep(0.05)
    finally:
        # However the wedge ends (deadline or shutdown abort), the seconds
        # actually spent wedged are stall badput, not queue_wait.
        goodput.note_stall(time.monotonic() - wedge_started)


def maybe_sigterm(window_idx: int) -> None:
    """Deliver SIGTERM to this process after eval window N (`sigterm:N`)."""
    plan = get_plan()
    if plan is None:
        return
    at = plan.arg("sigterm")
    if at is not None and window_idx == at and plan.consume("sigterm"):
        _injected_counter().inc(labels={"fault": "sigterm"})
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_slow_compile() -> None:
    """Sleep `slow_compile:S` seconds inside the watchdog-guarded compile
    stage (one-shot). The sleep is plain Python, so the watchdog's
    interrupt_main() lands immediately — this drives the CompileStallError
    path deterministically where a real wedge would need real hardware."""
    plan = get_plan()
    if plan is None:
        return
    secs = plan.arg("slow_compile")
    if secs is None or not plan.consume("slow_compile"):
        return
    _injected_counter().inc(labels={"fault": "slow_compile"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] injecting %ds compile delay", secs
    )
    time.sleep(secs)


def maybe_host_loss(window_idx: int) -> None:
    """Freeze THIS process (SIGSTOP to itself: all threads — heartbeat
    publisher included — halt; sockets stay open) after dispatching eval
    window N when `host_loss:N` is armed. A freeze, not an exit: a host that
    CLOSES its sockets is detected and fatal-propagated by jax's own
    coordination service within milliseconds (every peer aborts, no
    checkpoint, no exit code) — the failure mode that NEEDS the fleet layer
    is the silent one, where nothing closes and every collective just stops
    answering. The fleet e2e harness arms this on ONE process; the
    survivors' recovery path is what's under test (the harness SIGKILLs the
    frozen victim at cleanup)."""
    plan = get_plan()
    if plan is None:
        return
    at = plan.arg("host_loss")
    if at is not None and window_idx == at and plan.consume("host_loss"):
        _injected_counter().inc(labels={"fault": "host_loss"})
        get_logger("stoix_tpu.resilience").warning(
            "[faultinject] host_loss at window %d — freezing (SIGSTOP) NOW",
            window_idx,
        )
        import sys

        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGSTOP)
        # Only reachable if something SIGCONTs the frozen process: the host
        # is still "lost" — finish the job.
        os._exit(EXIT_CODE_FAILURE)


def maybe_resize(window_idx: int) -> Optional[str]:
    """Return "shrink"/"grow" when a `shrink:N`/`grow:N` resize fault fires
    after eval window N (one-shot each), else None. This hook only DECIDES —
    the runner owns the exit protocol (secure the emergency snapshot, write
    `resize_request.json`, dump the flight record, exit 89) via
    resilience/elastic.py, because only the runner holds the fleet
    coordinator and the live step count."""
    plan = get_plan()
    if plan is None:
        return None
    for action in ("shrink", "grow"):
        at = plan.arg(action)
        if at is not None and window_idx == at and plan.consume(action):
            _injected_counter().inc(labels={"fault": action})
            get_logger("stoix_tpu.resilience").warning(
                "[faultinject] %s resize requested at window %d",
                action, window_idx,
            )
            flightrec.get_flight_recorder().record(
                "fault", fault=action, window=window_idx
            )
            return action
    return None


def maybe_host_stall(window_idx: int) -> None:
    """Sleep `host_stall:S` seconds at the top of eval window 1 (one-shot):
    a straggler host, alive and heartbeating but slow — the skew-telemetry
    failure mode, which host_loss (dead) cannot exercise."""
    plan = get_plan()
    if plan is None:
        return
    secs = plan.arg("host_stall")
    if secs is None or window_idx != 1 or not plan.consume("host_stall"):
        return
    _injected_counter().inc(labels={"fault": "host_stall"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] host stalling %ds at window %d", secs, window_idx
    )
    flightrec.get_flight_recorder().record(
        "fault", fault="host_stall", window=window_idx, seconds=float(secs)
    )
    time.sleep(secs)
    # The sleep is pure badput: charge it to the active run's goodput ledger
    # as stall so it cannot masquerade as compute residual.
    goodput.note_stall(float(secs))


def maybe_barrier_wedge(barrier: str, max_wedge_s: float = 3600.0) -> None:
    """Wedge (sleep, never arrive) instead of entering a fleet barrier when
    `barrier_wedge` is armed (one-shot) — drives the barrier deadline
    watchdog's FleetBarrierTimeout deterministically. The sleep is plain
    Python, so the watchdog's interrupt_main() lands immediately."""
    plan = get_plan()
    if plan is None:
        return
    if plan.arg("barrier_wedge") is None or not plan.consume("barrier_wedge"):
        return
    _injected_counter().inc(labels={"fault": "barrier_wedge"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] wedging instead of arriving at barrier %r", barrier
    )
    # Sliced sleep (like maybe_stall_queue): interrupt_main only raises
    # BETWEEN bytecodes, so the barrier watchdog's interrupt must find a
    # bytecode boundary — one monolithic sleep would absorb it for the
    # full wedge duration.
    deadline = time.monotonic() + max_wedge_s
    while time.monotonic() < deadline:
        time.sleep(0.05)


# Top-mantissa-bit position per float dtype: flipping it perturbs the value
# by ~50% relative — large enough that the very next `params + update`
# cannot round the divergence away (a LOW mantissa flip of a near-zero
# param is a denormal that evaporates on the first add; a real HBM flip can
# land anywhere, and the sentinel must be proven against one that STICKS).
_TOP_MANTISSA_BIT = {"float16": 9, "bfloat16": 6, "float32": 22, "float64": 51}


def _flip_one_replica(leaf: Any) -> Any:
    """Rebuild a fully-replicated jax.Array with the lowest-id LOCAL device's
    copy differing by ONE flipped mantissa bit (top mantissa bit of the
    largest-magnitude element) — the bit surgery behind `bitflip:N`. The
    sharding still CLAIMS replication; nothing in jax checks the buffers
    agree, which is exactly the silent-corruption hole the integrity
    sentinel exists to close. The result is finite: an exponent/sign flip
    could produce inf and be caught by the PR 3 guards — the class under
    test is finite-but-wrong."""
    import jax

    devices = sorted(leaf.sharding.addressable_devices, key=lambda d: d.id)
    host = np.asarray(leaf.addressable_data(0))
    flipped = np.array(host, copy=True)
    width = {2: np.uint16, 4: np.uint32, 8: np.uint64}[flipped.dtype.itemsize]
    shift = _TOP_MANTISSA_BIT.get(str(flipped.dtype), 0)
    magnitude = np.abs(flipped.astype(np.float64, copy=False))
    element = int(np.argmax(magnitude)) if flipped.size else 0
    bits = flipped.view(width)
    bits.flat[element] ^= width(1 << shift)
    target = devices[0] if jax.process_index() == 0 else None
    shards = [
        jax.device_put(flipped if device == target else host, device)
        for device in devices
    ]
    return jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, shards
    )


def maybe_bitflip(state: Any, window_idx: int) -> Any:
    """Flip one mantissa bit in one replica's params going INTO eval window N
    when `bitflip:N` is armed (one-shot); returns the (possibly rebuilt)
    state. The chosen leaf is the first fully-replicated floating leaf whose
    tree-path mentions 'param' (fallback: any fully-replicated float leaf).
    Unarmed this is a single None check — zero work, zero host syncs."""
    plan = get_plan()
    if plan is None:
        return state
    at = plan.arg("bitflip")
    if at is None or window_idx != at or not plan.consume("bitflip"):
        return state
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(state)

    def eligible(leaf: Any) -> bool:
        return (
            isinstance(leaf, jax.Array)
            and jax.numpy.issubdtype(leaf.dtype, jax.numpy.floating)
            and leaf.dtype.itemsize in (2, 4, 8)
            and leaf.sharding.is_fully_replicated
        )

    # Prefer the LARGEST eligible leaf of the top-level params group (a
    # weight matrix, nonzero after init) over biases/scalars: the flip must
    # be numerically persistent through the next update, not a denormal that
    # rounds away on the first add. Fallback: any path mentioning 'param'
    # (optax moments nest a 'params' dict), then any replicated float leaf.
    def _ranked(predicate):
        return [
            (leaf.size, i) for i, (path, leaf) in enumerate(flat)
            if eligible(leaf) and predicate(jax.tree_util.keystr(path).lower())
        ]

    candidates = (
        _ranked(lambda key: key.startswith(".params") or key.startswith("['params']"))
        or _ranked(lambda key: "param" in key)
        or _ranked(lambda key: True)
    )
    target_idx = max(candidates, default=(0, None))[1]
    if target_idx is None:
        get_logger("stoix_tpu.resilience").warning(
            "[faultinject] bitflip armed but the state has no fully-"
            "replicated float leaf to corrupt — skipping"
        )
        return state
    path, leaf = flat[target_idx]
    _injected_counter().inc(labels={"fault": "bitflip"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] flipping one mantissa bit of %s on one replica going "
        "into window %d", jax.tree_util.keystr(path), window_idx,
    )
    leaves = [entry for _path, entry in flat]
    leaves[target_idx] = _flip_one_replica(leaf)
    return treedef.unflatten(leaves)


def maybe_poison_swap(params: Any) -> Any:
    """Write NaN into the first float leaf of a hot-swap candidate when
    `swap_poison` is armed (one-shot) — the non-finite-restore case the
    serving canary must reject. Returns the (possibly poisoned) tree."""
    plan = get_plan()
    if plan is None:
        return params
    if plan.arg("swap_poison") is None or not plan.consume("swap_poison"):
        return params
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        if np.issubdtype(arr.dtype, np.floating):
            poisoned = np.array(arr, copy=True)
            poisoned.flat[0] = np.nan
            leaves[i] = poisoned
            _injected_counter().inc(labels={"fault": "swap_poison"})
            get_logger("stoix_tpu.resilience").warning(
                "[faultinject] poisoned hot-swap candidate with NaN"
            )
            return treedef.unflatten(leaves)
    return params


def consume_replica_kill() -> Optional[int]:
    """The serve-fleet replica ordinal to hard-close mid-traffic when
    `replica_kill:N` is armed (one-shot), else None. The loop runner polls
    this from its traffic thread and closes the named replica — in-flight
    requests complete with ServerClosedError and the router's failover path
    must re-dispatch them (docs/DESIGN.md §2.15)."""
    plan = get_plan()
    if plan is None:
        return None
    at = plan.arg("replica_kill")
    if at is None or not plan.consume("replica_kill"):
        return None
    _injected_counter().inc(labels={"fault": "replica_kill"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] killing serve replica %d mid-traffic", at
    )
    flightrec.get_flight_recorder().record(
        "fault", fault="replica_kill", replica=int(at)
    )
    return at


def maybe_slow_replica(replica_id: int) -> None:
    """Sleep `replica_slow:S` MILLISECONDS before each batch on serve-fleet
    replica 0 (sustained — a straggler replica keeps straggling; counted and
    logged once). Other replicas, and the plain single-server path (which
    passes no replica id), are untouched."""
    plan = get_plan()
    if plan is None:
        return
    ms = plan.arg("replica_slow")
    if ms is None or replica_id != 0:
        return
    if plan.consume("replica_slow"):
        _injected_counter().inc(labels={"fault": "replica_slow"})
        get_logger("stoix_tpu.resilience").warning(
            "[faultinject] replica 0 straggling: +%dms per batch", ms
        )
        flightrec.get_flight_recorder().record(
            "fault", fault="replica_slow", ms=int(ms)
        )
    time.sleep(ms / 1000.0)


def maybe_stall_feedback(should_abort: Optional[Callable[[], bool]] = None) -> None:
    """Wedge the experience recorder's replay feeder `feedback_stall:S`
    seconds (one-shot) — a stalled replay ingest. Sliced sleep so shutdown
    (`should_abort`) cuts it short; the stall is charged to the goodput
    ledger as badput either way."""
    plan = get_plan()
    if plan is None:
        return
    secs = plan.arg("feedback_stall")
    if secs is None or not plan.consume("feedback_stall"):
        return
    _injected_counter().inc(labels={"fault": "feedback_stall"})
    get_logger("stoix_tpu.resilience").warning(
        "[faultinject] stalling experience feedback for %ds", secs
    )
    flightrec.get_flight_recorder().record(
        "fault", fault="feedback_stall", seconds=float(secs)
    )
    deadline = time.monotonic() + float(secs)
    while time.monotonic() < deadline:
        if should_abort is not None and should_abort():
            break
        time.sleep(0.05)
    goodput.note_stall(float(secs))


def backend_wedge_armed() -> bool:
    """Whether the probe-subprocess wedge is armed. The wedge itself fires in
    the CHILD (resilience/preflight.py inlines the check — the child inherits
    STOIX_TPU_FAULT); this parent-side view exists for logging/tests."""
    plan = get_plan()
    return plan is not None and plan.arg("backend_wedge") is not None


def ckpt_corrupt_armed() -> bool:
    plan = get_plan()
    return plan is not None and plan.arg("ckpt_corrupt") is not None


def consume_ckpt_corrupt() -> bool:
    plan = get_plan()
    return plan is not None and plan.consume("ckpt_corrupt")


def corrupt_checkpoint_files(step_dir: str) -> int:
    """Overwrite the checkpoint payload files under `step_dir` with garbage
    bytes (truncation + bad magic), returning how many files were mangled.
    `_CHECKPOINT_METADATA` and the `metrics/` item are left intact: orbax
    parses BOTH when merely CONSTRUCTING a manager over the directory, and a
    run must be able to OPEN a corrupt checkpoint store to fall back past it
    — the realistic preemption victim is the (large, slow-to-write) array
    payload, not the tiny metadata files. Used by the `ckpt_corrupt` fault
    and directly by tests."""
    mangled = 0
    for root, _dirs, files in os.walk(step_dir):
        if "metrics" in os.path.relpath(root, step_dir).split(os.sep):
            continue
        for name in sorted(files):
            if name == "_CHECKPOINT_METADATA":
                continue
            path = os.path.join(root, name)
            try:
                with open(path, "wb") as f:
                    f.write(b"\x00CORRUPTED-BY-FAULT-INJECTION\x00")
                mangled += 1
            except OSError:  # noqa: STX003 — chaos must not crash the host loop
                pass
    if mangled:
        _injected_counter().inc(labels={"fault": "ckpt_corrupt"})
        get_logger("stoix_tpu.resilience").warning(
            "[faultinject] corrupted %d file(s) under %s", mangled, step_dir
        )
    return mangled
