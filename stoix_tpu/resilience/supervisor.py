"""Sebulba actor supervision: restart crashed actors, fail fast on the rest.

Before this layer, a crashed actor thread logged a traceback and stopped the
whole run's lifetime; a WEDGED actor (alive but silent) hung the learner
until the 180 s collect timeout. The supervisor owns the actor threads
instead:

  * a crash is reported by the dying thread (rollout_thread); the supervisor
    respawns a replacement — fresh thread, fresh env instance (the thread
    factory re-invokes the env factory), re-fetched params (the param queue
    is re-primed with the latest distributed params so the replacement never
    deadlocks against a learner that is itself blocked waiting for the
    replacement's rollout) — with bounded exponential backoff;
  * past `max_restarts`, the failure is UNRECOVERABLE: a typed
    ComponentFailure poison-pill goes through the OnPolicyPipeline so the
    learner raises on its next collect instead of timing out;
  * the heartbeat watchdog (PR-2 HeartbeatBoard) detects the silent-wedge
    case — an actor thread that is alive but has stopped beating for
    `wedge_timeout_s` — and routes it down the same poison-pill path
    (a Python thread cannot be killed, so a wedge is never restartable).

Restarts change WHICH env steps feed the learner (the replacement re-seeds
its envs), so supervision never fires on a healthy run — with no crashes the
training stream is untouched (the bit-identity guarantee of the resilience
layer's defaults).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from stoix_tpu.observability import (
    HeartbeatBoard,
    flightrec,
    get_logger,
    get_registry,
    goodput,
)
from stoix_tpu.resilience.errors import ComponentFailure

ThreadFactory = Callable[[], threading.Thread]


class ActorSupervisor:
    def __init__(
        self,
        lifetime: Any,
        pipeline: Any,
        param_server: Any = None,
        max_restarts: int = 2,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 10.0,
        wedge_timeout_s: float = 0.0,
    ) -> None:
        self._lifetime = lifetime
        self._pipeline = pipeline
        self._param_server = param_server
        self.max_restarts = int(max_restarts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self._lock = threading.Lock()
        self._factories: Dict[int, ThreadFactory] = {}
        self._threads: Dict[int, threading.Thread] = {}
        self._restarts: Dict[int, int] = {}
        self._spawned_at: Dict[int, float] = {}
        self._failed: set = set()
        self._watchdog: Optional[threading.Thread] = None
        registry = get_registry()
        self._restart_counter = registry.counter(
            "stoix_tpu_resilience_actor_restarts_total",
            "Crashed Sebulba actors respawned by the supervisor",
        )
        self._failure_counter = registry.counter(
            "stoix_tpu_resilience_component_failures_total",
            "Unrecoverable component failures propagated as poison-pills",
        )
        self._log = get_logger("stoix_tpu.resilience")

    # -- thread ownership ----------------------------------------------------
    def register(self, actor_id: int, factory: ThreadFactory) -> threading.Thread:
        """Own and start actor `actor_id`; `factory` must build a FRESH
        (unstarted) thread each call — it is re-invoked on every restart."""
        thread = factory()
        with self._lock:
            self._factories[actor_id] = factory
            self._threads[actor_id] = thread
            self._spawned_at[actor_id] = time.monotonic()
        thread.start()
        return thread

    def threads(self) -> Dict[int, threading.Thread]:
        with self._lock:
            return dict(self._threads)

    def restart_count(self, actor_id: Optional[int] = None) -> int:
        with self._lock:
            if actor_id is not None:
                return self._restarts.get(actor_id, 0)
            return sum(self._restarts.values())

    def join_all(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        for thread in self.threads().values():
            thread.join(timeout=max(0.0, deadline - time.monotonic()))

    # -- crash path ----------------------------------------------------------
    def report_crash(self, actor_id: int, exc: BaseException) -> None:
        """Called from the dying actor thread. Either schedules a supervised
        restart (bounded exponential backoff, off the dying thread) or
        propagates an unrecoverable ComponentFailure."""
        if self._lifetime.should_stop():
            return  # orderly shutdown already in progress; not a failure
        with self._lock:
            if actor_id in self._failed:
                return
            attempt = self._restarts.get(actor_id, 0)
            if attempt >= self.max_restarts:
                self._failed.add(actor_id)
                give_up = True
            else:
                self._restarts[actor_id] = attempt + 1
                give_up = False
        if give_up:
            self._propagate(
                actor_id,
                ComponentFailure(
                    f"actor-{actor_id}",
                    f"crashed {attempt + 1} time(s), max_restarts={self.max_restarts} exhausted",
                    exc,
                ),
            )
            return
        delay = min(self.backoff_base_s * (2.0 ** attempt), self.backoff_max_s)
        flightrec.get_flight_recorder().record(
            "actor_crash", actor=actor_id, error=f"{type(exc).__name__}: {exc}",
            attempt=attempt + 1, backoff_s=delay,
        )
        self._log.warning(
            "[supervisor] actor-%d crashed (%s: %s) — restarting in %.2fs "
            "(attempt %d/%d)",
            actor_id, type(exc).__name__, exc, delay, attempt + 1, self.max_restarts,
        )
        threading.Thread(
            target=self._respawn,
            args=(actor_id, delay),
            name=f"supervisor-respawn-{actor_id}",
            daemon=True,
        ).start()

    def _respawn(self, actor_id: int, delay: float) -> None:
        # The respawn thread OWNS the restart obligation: if anything below
        # raises (a reprime against a torn-down param server, a factory whose
        # env construction fails), dying silently would leave the learner
        # blocked in collect_rollouts until its 180 s timeout with no
        # evidence — the exact no-typed-error-path shape STX016 polices on
        # futures. Convert any failure into the ComponentFailure poison-pill.
        try:
            self._respawn_inner(actor_id, delay)
        except Exception as exc:  # noqa: BLE001 — every respawn failure must
            # surface as a typed poison-pill, whatever raised it
            with self._lock:
                already = actor_id in self._failed
                self._failed.add(actor_id)
            if not already:
                self._propagate(
                    actor_id,
                    ComponentFailure(
                        f"actor-{actor_id}",
                        f"respawn failed ({type(exc).__name__}: {exc})",
                        exc,
                    ),
                )

    def _respawn_inner(self, actor_id: int, delay: float) -> None:
        deadline = time.monotonic() + delay
        while time.monotonic() < deadline:
            if self._lifetime.should_stop():
                return
            time.sleep(0.02)
        if self._lifetime.should_stop():
            return
        # Re-prime params FIRST: the learner may already be blocked in
        # collect_rollouts waiting for this very actor, in which case it will
        # never push params again — the replacement must not deadlock on an
        # empty param queue.
        if self._param_server is not None:
            self._param_server.reprime(actor_id)
        with self._lock:
            factory = self._factories.get(actor_id)
        if factory is None:
            return
        thread = factory()
        with self._lock:
            self._threads[actor_id] = thread
            self._spawned_at[actor_id] = time.monotonic()
        thread.start()
        # The backoff+respawn wall time is recovery in the goodput ledger:
        # the fleet was degraded (one actor down) for exactly this span.
        goodput.note_recovery(delay)
        self._restart_counter.inc(labels={"actor": str(actor_id)})
        self._log.warning(
            "[supervisor] actor-%d restarted (fresh env instance, re-primed params)",
            actor_id,
        )

    def _propagate(self, actor_id: int, failure: ComponentFailure) -> None:
        self._failure_counter.inc(labels={"component": failure.component})
        flightrec.get_flight_recorder().record(
            "component_failure", component=failure.component, detail=str(failure)
        )
        self._log.error("[supervisor] %s", failure)
        # Learner side: poison the rollout hand-off so collect_rollouts
        # raises instead of burning its timeout.
        self._pipeline.fail(actor_id, failure)
        # Actor side: poison the failed actor's OWN param queue — a wedged
        # actor blocked in get_params dies with the typed failure instead of
        # lingering until process exit.
        if self._param_server is not None:
            self._param_server.fail(failure, actor_id=actor_id)

    # -- wedge path ----------------------------------------------------------
    def start_watchdog(self, heartbeats: HeartbeatBoard, poll_interval_s: float = 0.5) -> None:
        """Poll heartbeat ages for owned actors; an actor that is ALIVE but
        silent for `wedge_timeout_s` is wedged — unrestartable (threads can't
        be killed), so it goes straight down the poison-pill path. No-op when
        wedge_timeout_s <= 0. Actors that have not beaten since their latest
        (re)spawn get 4x the budget measured from that spawn: first-rollout
        compile can dwarf the steady-state cadence, and a freshly RESTARTED
        actor must not be judged against the stale pre-crash beat."""
        if self.wedge_timeout_s <= 0 or self._watchdog is not None:
            return

        def _watch() -> None:
            while not self._lifetime.should_stop():
                time.sleep(poll_interval_s)
                try:
                    self._watch_once(heartbeats)
                except Exception:  # noqa: BLE001 — a poll that raises must
                    # not silently disarm wedge detection for the rest of
                    # the run; log, count, keep polling.
                    import traceback

                    get_registry().counter(
                        "stoix_tpu_resilience_watchdog_errors_total",
                        "Supervisor wedge-watchdog polls that raised",
                    ).inc()
                    self._log.error(
                        "[supervisor] wedge-watchdog poll FAILED "
                        "(detection still armed):\n%s", traceback.format_exc(),
                    )

        self._watchdog = threading.Thread(
            target=_watch, name="supervisor-watchdog", daemon=True
        )
        self._watchdog.start()

    def _watch_once(self, heartbeats: HeartbeatBoard) -> None:
        for actor_id, thread in self.threads().items():
            with self._lock:
                if actor_id in self._failed:
                    continue
                spawned_at = self._spawned_at.get(actor_id)
            if not thread.is_alive():
                continue  # crash path owns dead threads
            age = heartbeats.age(f"actor-{actor_id}")
            since_spawn = (
                time.monotonic() - spawned_at
                if spawned_at is not None
                else age
            )
            if age is None or (since_spawn is not None and age > since_spawn):
                # No beat since the latest (re)spawn: grade the fresh
                # thread on its own clock, with compile headroom.
                age = since_spawn if since_spawn is not None else 0.0
                budget = 4.0 * self.wedge_timeout_s
            else:
                budget = self.wedge_timeout_s
            if age <= budget:
                continue
            with self._lock:
                if actor_id in self._failed:
                    continue
                self._failed.add(actor_id)
            self._propagate(
                actor_id,
                ComponentFailure(
                    f"actor-{actor_id}",
                    f"wedged: thread alive but silent for {age:.1f}s "
                    f"(wedge_timeout_s={self.wedge_timeout_s})",
                ),
            )


def supervisor_from_config(
    config: Any, lifetime: Any, pipeline: Any, param_server: Any = None
) -> Optional[ActorSupervisor]:
    """Build from the `arch.supervision` block; None when disabled. Defaults
    (enabled, 2 restarts, no wedge detection) are safe for healthy runs:
    supervision only acts when a component actually fails."""
    sup_cfg = config.arch.get("supervision") or {}
    if not bool(sup_cfg.get("enabled", True)):
        return None
    return ActorSupervisor(
        lifetime,
        pipeline,
        param_server=param_server,
        max_restarts=int(sup_cfg.get("max_restarts", 2)),
        backoff_base_s=float(sup_cfg.get("backoff_base_s", 0.5)),
        backoff_max_s=float(sup_cfg.get("backoff_max_s", 10.0)),
        wedge_timeout_s=float(sup_cfg.get("wedge_timeout_s", 0.0) or 0.0),
    )
