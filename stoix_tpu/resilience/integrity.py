"""State-integrity sentinel: silent-corruption detection (docs/DESIGN.md §2.9).

Anakin's correctness story rests on an invariant the Podracer design gives us
by construction but nothing ever checked: after every gradient `pmean`, the
replicated learner state (params, optimizer moments) is **bit-identical on
every device and host**. PR 3's guards catch non-finite updates and PR 7's
fleet layer catches dead/frozen hosts — but a flaky core or an HBM bit-flip
produces *finite-but-wrong* values that train silently to garbage. The
invariant makes this the cheapest failure class to detect: ANY cross-replica
disagreement is a proof of corruption. Three mechanisms:

  * **In-jit replica fingerprints** — a tiny shard_mapped program folds each
    replicated state group (params, opt state, ...) to a per-device uint32
    fingerprint (bitcast to words + a murmur-style position-salted mix),
    emitted as a `[num_devices]` vector that rides the runner's EXISTING
    coalesced metric fetch exactly like the fleet flag vector: the reduction
    is local to each device, so the check costs zero extra collectives. The
    host compares all entries once the window materializes; a mismatch
    raises a typed `StateCorruptionError` naming the deviating device(s),
    process(es), and state group(s). Because the materialized vector is
    REPLICATED data, every host computes the same verdict at the same
    window — corruption agreement falls out of the transport.
  * **Corruption agreement + quarantine** — `FLAG_CORRUPT` joins the fleet
    flag byte (resilience/fleet.py) so the stop reason is visible in votes
    and stop-request telemetry; the sentinel's excepthook translates an
    uncaught StateCorruptionError into `EXIT_CODE_STATE_CORRUPTION` (88),
    distinct from the fleet-partition 87, and records the offending host in
    a quarantine file together with the resume overrides a supervising
    launcher needs (`launcher.py --supervise` relaunches on 88 and restores
    the newest digest-verified checkpoint).
  * **Determinism probe** (optional) — records one (state, minibatch-stream)
    input at the first window plus the fingerprint of the learn step's
    output, then periodically replays the SAME input through the SAME
    compiled program and compares fingerprints bitwise. A wrong-math core
    is caught even at replica count 1, where no cross-replica disagreement
    can exist. Costs one held state copy plus one learn execution per probe.

This module is also the shared home of the per-leaf sha256 **digest
manifest** the fleet emergency store introduced (PR 7): `leaf_digest` /
`digest_arrays` / `verify_digests` are used by the emergency store, by every
orbax save (utils/checkpointing.py writes a `_digests.json` sidecar and
`restore` verifies it, rejecting on-disk bit-rot instead of resuming it),
and by the serving loader's hot-swap canary (serve/).

Everything sits behind `arch.integrity` (off — the default — adds zero ops,
zero host work: the host loops are bit-identical, pinned by
tests/test_integrity.py). jax is imported lazily so digest helpers stay
usable from no-jax paths (bench --check).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from stoix_tpu.observability import flightrec, get_logger, get_registry
from stoix_tpu.resilience.errors import StateCorruptionError

# Exit code of the corruption path: distinct from the watchdog's 86 and the
# fleet partition's 87 so `launcher.py --supervise` can tell "this host's
# STATE is corrupt — restore a digest-verified checkpoint and quarantine the
# offender" apart from "a peer died" (docs/DESIGN.md §2.6 exit-code table).
# Declared in the canonical registry (resilience/exit_codes.py, STX018);
# re-exported here because this module has owned the name since PR 12.
from stoix_tpu.resilience.exit_codes import EXIT_CODE_STATE_CORRUPTION

_GOLDEN = 0x9E3779B9  # 32-bit golden-ratio constant (position/group salt)


# ---------------------------------------------------------------------------
# Digest manifest helpers (shared: fleet emergency store, orbax sidecar,
# serving canary). sha256 over the raw host bytes — dtype-exact, so a single
# flipped bit anywhere in a leaf fails verification.
# ---------------------------------------------------------------------------


def leaf_digest(arr: np.ndarray) -> str:
    """sha256 hex digest of a host array's raw bytes (C-contiguous view)."""
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def digest_arrays(arrays: Dict[str, np.ndarray]) -> Dict[str, str]:
    """Per-leaf digest record for a {key: host array} mapping."""
    return {key: leaf_digest(arr) for key, arr in arrays.items()}


def verify_digests(
    arrays: Dict[str, np.ndarray], record: Dict[str, str]
) -> List[str]:
    """Keys present in BOTH `arrays` and `record` whose bytes no longer match
    the recorded digest (empty list = verified). Keys absent from either side
    are not this function's verdict — the caller decides whether a missing
    leaf is corruption (orbax restore: yes) or topology (emergency store)."""
    return sorted(
        key
        for key, want in record.items()
        if key in arrays and leaf_digest(np.asarray(arrays[key])) != want
    )


# ---------------------------------------------------------------------------
# Settings
# ---------------------------------------------------------------------------


class IntegritySettings(NamedTuple):
    """Resolved `arch.integrity` config block (defaults applied)."""

    enabled: bool
    determinism_probe_interval: int
    quarantine_file: str


def settings_from_config(config: Any) -> IntegritySettings:
    cfg = (config.get("arch") or {}).get("integrity") or {}
    return IntegritySettings(
        enabled=bool(cfg.get("enabled", False)),
        determinism_probe_interval=int(cfg.get("determinism_probe_interval", 0) or 0),
        quarantine_file=str(
            cfg.get("quarantine_file") or os.path.join("checkpoints", "quarantine.json")
        ),
    )


# ---------------------------------------------------------------------------
# In-jit fingerprints
# ---------------------------------------------------------------------------


def _fmix32(x: Any) -> Any:
    """murmur3's 32-bit finalizer: a bijective avalanche mix, so any change
    to any input word changes the mixed word (uint32 arithmetic wraps)."""
    import jax.numpy as jnp

    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _leaf_words(leaf: Any) -> Any:
    """A leaf's raw bits as a flat uint32 word vector: bool widens to uint8,
    multi-byte dtypes BITCAST to uint8 (exact bytes — a mantissa flip is a
    word change, never rounded away), then widen to uint32 for the mix."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(leaf)
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    if x.dtype.itemsize > 1:
        x = jax.lax.bitcast_convert_type(x, jnp.uint8)
    return x.reshape(-1).astype(jnp.uint32)


def fingerprint_leaves(leaves: Sequence[Any], salt: int = 0) -> Any:
    """Fold a list of array leaves to ONE uint32 fingerprint (traceable,
    collective-free — safe to call per-device inside shard_map). Each word is
    salted by its position and its leaf's index before the avalanche mix, so
    a flip is detected wherever it lands and two identical flips at
    different positions cannot cancel."""
    import jax
    import jax.numpy as jnp

    acc = jnp.uint32(salt & 0xFFFFFFFF)
    for leaf_idx, leaf in enumerate(leaves):
        words = _leaf_words(leaf)
        position = jax.lax.iota(jnp.uint32, words.size)
        leaf_salt = jnp.uint32(((leaf_idx + 1) * _GOLDEN) & 0xFFFFFFFF)
        mixed = _fmix32(words ^ _fmix32(position + leaf_salt))
        acc = _fmix32(
            (acc + jnp.sum(mixed, dtype=jnp.uint32)) ^ jnp.uint32(words.size & 0xFFFFFFFF)
        )
    return acc


def _is_fingerprintable(leaf: Any) -> bool:
    """Template-side gate: a fully-replicated device array with a standard
    (bitcastable) dtype. Sharded leaves (per-shard keys, env state) are NOT
    replicas — disagreement there is data parallelism, not corruption."""
    import jax

    if not isinstance(leaf, jax.Array):
        return False
    try:
        if not leaf.sharding.is_fully_replicated:
            return False
    except Exception:  # noqa: BLE001 — deleted/donated arrays have no sharding
        return False
    return not jax.dtypes.issubdtype(leaf.dtype, jax.dtypes.extended)


def replicated_group_specs(template: Any) -> List[Tuple[str, List[int]]]:
    """The replicated state groups of a learner state: each top-level field
    (NamedTuple) or key (dict) whose subtree holds at least one fully
    replicated array leaf, with the flat indices of those leaves. Non-record
    states fold into a single 'state' group."""
    import jax

    if hasattr(template, "_fields"):
        named = [(name, getattr(template, name)) for name in template._fields]
    elif isinstance(template, dict):
        named = sorted(template.items())
    else:
        named = [("state", template)]
    groups: List[Tuple[str, List[int]]] = []
    for name, subtree in named:
        idxs = [
            i for i, leaf in enumerate(jax.tree.leaves(subtree))
            if _is_fingerprintable(leaf)
        ]
        if idxs:
            groups.append((str(name), idxs))
    return groups


def _group_subtree(state: Any, name: str) -> Any:
    if hasattr(state, "_fields"):
        return getattr(state, name)
    if isinstance(state, dict):
        return state[name]
    return state


def build_fingerprint_fn(
    mesh: Any, template: Any
) -> Tuple[Callable[[Any], Dict[str, Any]], List[str]]:
    """ONE jitted shard_mapped fingerprint program for `template`'s
    replicated groups (built once — never in a loop, STX012). Returns
    (fn, group_names); fn(state) -> {group: [num_devices] uint32 vector},
    entry i belonging to mesh.devices.flatten()[i] (the same decode
    convention as the fleet flag vector).

    Inputs enter with in_specs P() — they ARE replicated, so no resharding
    and no collective happens; each device folds ITS OWN copy of the bytes,
    which is exactly what makes a single-replica HBM flip visible. Outputs
    leave with the [1]-per-device block sharded over every mesh axis.
    check_vma=False: the output genuinely varies per device (that is the
    point), which the replication validator cannot express for replicated
    inputs."""
    import jax
    from jax.sharding import PartitionSpec

    from stoix_tpu.parallel.mesh import shard_map

    groups = replicated_group_specs(template)
    if not groups:
        raise ValueError(
            "state has no fully-replicated array leaves to fingerprint — "
            "arch.integrity cannot guard a state with no replicated groups"
        )
    axes = tuple(mesh.axis_names)

    def extract(state: Any) -> Dict[str, Tuple[Any, ...]]:
        out: Dict[str, Tuple[Any, ...]] = {}
        for name, idxs in groups:
            leaves = jax.tree.leaves(_group_subtree(state, name))
            out[name] = tuple(leaves[i] for i in idxs)
        return out

    def per_device(grouped: Dict[str, Tuple[Any, ...]]) -> Dict[str, Any]:
        out = {}
        for group_idx, (name, _) in enumerate(groups):
            fp = fingerprint_leaves(
                grouped[name], salt=((group_idx + 1) * _GOLDEN) & 0xFFFFFFFF
            )
            out[name] = fp[None]  # [1] per device -> [num_devices] global
        return out

    program = jax.jit(
        shard_map(
            per_device,
            mesh=mesh,
            in_specs=(PartitionSpec(),),
            out_specs=PartitionSpec(axes),
            check_vma=False,
        )
    )
    return (lambda state: program(extract(state))), [name for name, _ in groups]


# ---------------------------------------------------------------------------
# Sentinel
# ---------------------------------------------------------------------------


class StateIntegritySentinel:
    """Owns one run's integrity checking: the fingerprint program, the
    host-side verdicts, the determinism probe, the quarantine record, and
    the exit-code excepthook. Construct via `sentinel_from_config`; `bind`
    once the mesh + state template exist, `deactivate` in the host loop's
    finally."""

    def __init__(self, settings: IntegritySettings):
        self.settings = settings
        self._fp_fn: Optional[Callable[[Any], Dict[str, Any]]] = None
        self.group_names: List[str] = []
        self._device_order: List[Tuple[int, int]] = []  # (device_id, process)
        self._lock = threading.Lock()
        self._checks = 0
        self._overhead_s = 0.0
        self._probe_runs = 0
        self._probe_input: Optional[Any] = None
        self._probe_ref: Optional[Dict[str, np.ndarray]] = None
        self._resume_overrides: List[str] = []
        self._corruption: Optional[StateCorruptionError] = None
        self._prev_excepthook: Optional[Callable] = None
        self._log = get_logger("stoix_tpu.resilience")

    # -- lifecycle -----------------------------------------------------------
    def bind(self, mesh: Any, state_template: Any) -> "StateIntegritySentinel":
        """Build the fingerprint program for this mesh + state structure and
        record the device->process decode order."""
        self._fp_fn, self.group_names = build_fingerprint_fn(mesh, state_template)
        self._device_order = [
            (int(d.id), int(d.process_index)) for d in mesh.devices.flatten()
        ]
        probe_note = (
            f", determinism probe every "
            f"{self.settings.determinism_probe_interval} window(s)"
            if self.probe_enabled
            else ""
        )
        self._log.info(
            "[integrity] sentinel armed: fingerprinting %s across %d device(s)%s",
            "+".join(self.group_names), len(self._device_order), probe_note,
        )
        return self

    def install_excepthook(self) -> None:
        """Translate an uncaught StateCorruptionError into the corruption
        exit code for the supervising launcher (chains with — and takes
        precedence over — the fleet hook's FleetError->87, which a
        StateCorruptionError never matches)."""
        prev = sys.excepthook
        self._prev_excepthook = prev

        def hook(exc_type, exc, tb):
            prev(exc_type, exc, tb)
            if isinstance(exc, StateCorruptionError):
                # The quarantine path already dumped next to its record, but
                # THIS is the one place that actually dies with rc 88, and
                # os._exit skips every finally — so the exit path itself
                # must leave the evidence (STX021). A re-dump only
                # refreshes the ring snapshot.
                flightrec.dump_flight_record(
                    None,
                    reason=f"state corruption: uncaught {exc_type.__name__}",
                    exit_code=EXIT_CODE_STATE_CORRUPTION,
                )
                sys.stderr.flush()
                os._exit(EXIT_CODE_STATE_CORRUPTION)

        self._hook = hook
        sys.excepthook = hook

    def deactivate(self) -> None:
        """Restore the excepthook UNLESS a corruption verdict was recorded —
        the StateCorruptionError propagating out of the host loop after its
        finally is exactly what the hook must translate to exit code 88.
        Restores only when the installed hook is still OURS (another layer
        may have chained on top since install)."""
        if (
            self._corruption is None
            and self._prev_excepthook is not None
            and sys.excepthook is getattr(self, "_hook", None)
        ):
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None

    # -- resume/quarantine ----------------------------------------------------
    def set_resume_info(self, store_directory: str) -> None:
        """Record the overrides a relaunch needs to restore the newest
        digest-verified checkpoint of THIS run's orbax store
        (`<rel_dir>/<uid>/<model>` — Checkpointer.directory)."""
        directory = os.path.abspath(str(store_directory))
        uid_dir = os.path.dirname(directory)
        self._resume_overrides = [
            "logger.checkpointing.load_model=true",
            f"logger.checkpointing.load_args.load_path={os.path.dirname(uid_dir)}",
            f"logger.checkpointing.load_args.checkpoint_uid={os.path.basename(uid_dir)}",
        ]

    def _record_quarantine(self, err: StateCorruptionError) -> None:
        """Append the verdict to the quarantine file (read-modify-write):
        which process(es)/device(s) deviated, at which window/step, plus the
        resume overrides for `launcher.py --supervise`'s rc-88 relaunch. The
        scheduler (or operator) drains quarantined hosts; this repo's job is
        to NAME them with proof."""
        path = self.settings.quarantine_file
        entry = {
            "kind": err.kind,
            "groups": err.groups,
            "devices": err.devices,
            "processes": err.processes,
            "window": err.window,
            "step": err.step,
            "detail": err.detail,
            "unix_time": time.time(),
        }
        try:
            record = {"quarantined": [], "resume_overrides": []}
            if os.path.isfile(path):
                with open(path) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    record.update(loaded)
            record.setdefault("quarantined", []).append(entry)
            record["resume_overrides"] = list(self._resume_overrides)
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1)
            os.replace(tmp, path)
            self._log.error(
                "[integrity] quarantine record written to %s (process(es) %s, "
                "device(s) %s)", path, err.processes, err.devices,
            )
        except (OSError, ValueError) as exc:
            self._log.error(
                "[integrity] could not write quarantine record to %s: %s",
                path, exc,
            )
        # rc-88 flight record, next to the quarantine file (dumped even when
        # the quarantine write itself failed — the ring is all evidence then).
        recorder = flightrec.get_flight_recorder()
        recorder.record(
            "quarantine", corruption=err.kind, window=err.window, step=err.step,
            processes=list(err.processes), devices=list(err.devices),
        )
        flightrec.dump_flight_record(
            os.path.dirname(os.path.abspath(path)),
            reason=f"state corruption: {err.kind} at window {err.window}",
            exit_code=EXIT_CODE_STATE_CORRUPTION,
        )

    # -- fingerprints ---------------------------------------------------------
    @property
    def enabled(self) -> bool:
        return self.settings.enabled

    @property
    def probe_enabled(self) -> bool:
        return self.settings.determinism_probe_interval > 0

    def fingerprints(self, state: Any) -> Dict[str, Any]:
        """Dispatch the fingerprint program on `state` (device tree, to merge
        into the coalesced metric fetch). Host cost is dispatch only."""
        t0 = time.perf_counter()
        out = self._fp_fn(state)
        with self._lock:
            self._overhead_s += time.perf_counter() - t0
        return out

    def verify(
        self, payload: Dict[str, Any], window_idx: int, step: int
    ) -> Optional[StateCorruptionError]:
        """Compare a MATERIALIZED fingerprint payload's per-device entries.
        All equal -> None. Any disagreement -> the typed error naming the
        deviating device(s) (minority vs the majority fingerprint), with the
        quarantine record written. A pure function of replicated data, so
        every host reaches the same verdict at the same window."""
        t0 = time.perf_counter()
        bad_groups: List[str] = []
        deviant_positions: set = set()
        details: List[str] = []
        for name in self.group_names:
            vec = np.asarray(payload[name]).reshape(-1)
            values, counts = np.unique(vec, return_counts=True)
            if len(values) <= 1:
                continue
            bad_groups.append(name)
            if int(counts.max()) * 2 <= vec.size:
                # No STRICT majority (the 2-replica 1-vs-1 case, or worse):
                # corruption is still PROVEN — the replicas disagree — but
                # attribution is undecidable, and confidently quarantining
                # the numerically-smaller fingerprint would drain the
                # healthy host half the time. Name every device.
                deviant_positions.update(range(vec.size))
                details.append(
                    f"{name}: no majority fingerprint ("
                    + ", ".join(
                        f"device {self._device_order[i][0]}={int(vec[i]):#010x}"
                        for i in range(vec.size)
                    )
                    + ") — replicas disagree but the corrupt one is "
                    "undecidable at this replica count"
                )
                continue
            majority = values[int(np.argmax(counts))]
            deviants = np.nonzero(vec != majority)[0]
            deviant_positions.update(int(i) for i in deviants)
            details.append(
                f"{name}: majority fingerprint {int(majority):#010x} on "
                f"{int(counts.max())}/{vec.size} device(s), deviating "
                + ", ".join(
                    f"device {self._device_order[i][0]}={int(vec[i]):#010x}"
                    for i in deviants
                )
            )
        with self._lock:
            self._checks += 1
            self._overhead_s += time.perf_counter() - t0
        if not bad_groups:
            return None
        devices = sorted({self._device_order[i][0] for i in deviant_positions})
        processes = sorted({self._device_order[i][1] for i in deviant_positions})
        err = StateCorruptionError(
            kind="replica_mismatch",
            groups=bad_groups,
            devices=devices,
            processes=processes,
            window=window_idx,
            step=step,
            detail="; ".join(details),
        )
        self._corruption = err
        get_registry().counter(
            "stoix_tpu_integrity_corruptions_total",
            "Silent-corruption verdicts raised by the state-integrity sentinel",
        ).inc(labels={"kind": "replica_mismatch"})
        self._record_quarantine(err)
        self._log.error("[integrity] %s", err)
        return err

    def check_state(
        self, state: Any, window_idx: int, step: int
    ) -> Optional[StateCorruptionError]:
        """Synchronous fingerprint + verify (the Sebulba eval-boundary path,
        where there is no coalesced device fetch to piggyback on)."""
        payload = {
            name: np.asarray(value)
            for name, value in self.fingerprints(state).items()
        }
        return self.verify(payload, window_idx, step)

    # -- determinism probe ----------------------------------------------------
    def capture_probe_input(self, state_copy: Any) -> None:
        """Record the replay input (an on-device COPY the caller owns — the
        learn step donates its argument, so every replay runs on a fresh copy
        of this one). First capture wins."""
        if self.probe_enabled and self._probe_input is None:
            self._probe_input = state_copy

    def record_probe_reference(self, payload: Dict[str, Any]) -> None:
        """Record the reference output fingerprint — the FIRST window's own
        materialized fingerprint vector, which by construction is
        fingerprint(learn(probe_input)): the recording costs nothing."""
        if self.probe_enabled and self._probe_ref is None:
            self._probe_ref = {
                name: np.array(np.asarray(payload[name]), copy=True)
                for name in self.group_names
            }

    def should_probe(self, window_idx: int) -> bool:
        interval = self.settings.determinism_probe_interval
        return (
            self.probe_enabled
            and window_idx > 0
            and window_idx % interval == 0
            and self._probe_input is not None
            and self._probe_ref is not None
        )

    def run_probe(
        self, learn_fn: Callable[[Any], Any], tree_copy: Callable[[Any], Any]
    ) -> Optional[StateCorruptionError]:
        """Replay the recorded input through the learn step and compare the
        output fingerprint vector BITWISE against the recorded reference. A
        divergence means the same program on the same input computed a
        different answer — a wrong-math core, caught even at replica count 1.
        Synchronous (one extra learn execution); returns the typed error or
        None."""
        replay = learn_fn(tree_copy(self._probe_input))
        state = getattr(replay, "learner_state", replay)
        got = {
            name: np.asarray(value)
            for name, value in self.fingerprints(state).items()
        }
        with self._lock:
            self._probe_runs += 1
        mismatched = [
            name for name in self.group_names
            if not np.array_equal(got[name], self._probe_ref[name])
        ]
        if not mismatched:
            return None
        err = StateCorruptionError(
            kind="determinism",
            groups=mismatched,
            devices=[d for d, _ in self._device_order],
            processes=sorted({p for _, p in self._device_order}),
            window=-1,
            step=-1,
            detail="; ".join(
                f"{name}: replay {got[name].tolist()} != recorded "
                f"{self._probe_ref[name].tolist()}"
                for name in mismatched
            ),
        )
        self._corruption = err
        get_registry().counter(
            "stoix_tpu_integrity_corruptions_total",
            "Silent-corruption verdicts raised by the state-integrity sentinel",
        ).inc(labels={"kind": "determinism"})
        self._record_quarantine(err)
        self._log.error("[integrity] %s", err)
        return err

    # -- reporting ------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """The bench/LAST_RUN_STATS view of this run's sentinel activity."""
        with self._lock:
            return {
                "enabled": True,
                "fingerprint_checks": self._checks,
                "overhead_s": round(self._overhead_s, 6),
                "probe_runs": self._probe_runs,
            }


def disabled_stats() -> Dict[str, Any]:
    """The stats dict shape when the sentinel is off (bench schema parity)."""
    return {
        "enabled": False,
        "fingerprint_checks": 0,
        "overhead_s": 0.0,
        "probe_runs": 0,
    }


def sentinel_from_config(config: Any) -> Optional[StateIntegritySentinel]:
    """A bind-able sentinel when `arch.integrity.enabled`, else None (zero
    work, bit-identical host loops)."""
    settings = settings_from_config(config)
    if not settings.enabled:
        return None
    return StateIntegritySentinel(settings)


# ---------------------------------------------------------------------------
# Launcher-side helpers (no jax import)
# ---------------------------------------------------------------------------


def read_quarantine(path: str) -> Dict[str, Any]:
    """The quarantine record at `path` ({} when absent/unreadable)."""
    try:
        with open(path) as f:
            loaded = json.load(f)
        return loaded if isinstance(loaded, dict) else {}
    except (OSError, ValueError):
        return {}


def corruption_resume_overrides(quarantine_file: str) -> List[str]:
    """The resume overrides the latest corruption verdict recorded for a
    supervised relaunch ([] when the run had no checkpoint store — the
    relaunch then starts fresh)."""
    return [str(o) for o in read_quarantine(quarantine_file).get("resume_overrides") or []]
