"""Preemption-safe graceful stop (docs/DESIGN.md §2.3).

TPU fleet schedulers (and SLURM with `--signal=TERM@grace`) deliver SIGTERM
shortly before reclaiming a slot. Without handling, a mid-window SIGTERM
kills the process and throws away up to a full checkpoint interval of work.
`PreemptionHandler` converts SIGTERM/SIGINT into a REQUEST: the host loop
checks `stop_requested()` at each window boundary, drains the pipelined
dispatcher, writes an emergency checkpoint, and returns normally (exit code
0) so the run can auto-resume from the saved state.

Signal-handler discipline: the handler body only writes plain attributes
(GIL-atomic) — no locks, no logging, no registry calls — because Python runs
handlers between bytecodes of the MAIN thread, and re-entering a lock the
interrupted frame holds would deadlock. Counters and log lines are emitted by
the consumer (the host loop) after it observes the flag. A second signal
restores the previous handler and re-raises, so a stuck drain can still be
killed interactively.

Installation is a no-op (with a warning) outside the main thread: Sebulba's
learner loop runs in the main thread, but embedders driving experiments from
worker threads keep their own signal ownership.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Dict, Optional

from stoix_tpu.observability import get_logger, get_registry

_HANDLED = (signal.SIGTERM, signal.SIGINT)


class PreemptionHandler:
    """Graceful-stop flag fed by SIGTERM/SIGINT. Use as a context manager or
    via install()/uninstall(); always uninstall so later code (pytest, a
    second experiment) sees the original handlers."""

    def __init__(self) -> None:
        self._flag = False
        self._signum: Optional[int] = None
        self._prev: Dict[int, object] = {}
        self._installed = False

    # -- signal side (async-signal-safe: attribute writes only) --------------
    def _on_signal(self, signum, frame) -> None:
        if self._flag:
            # Second signal: the operator really means it. Put the previous
            # handler back and re-deliver so default semantics (kill /
            # KeyboardInterrupt) apply immediately.
            prev = self._prev.get(signum)
            signal.signal(signum, prev if prev is not None else signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        self._flag = True
        self._signum = signum

    # -- host-loop side ------------------------------------------------------
    def install(self) -> "PreemptionHandler":
        if threading.current_thread() is not threading.main_thread():
            get_logger("stoix_tpu.resilience").warning(
                "[preemption] not the main thread — signal handlers not "
                "installed; graceful preemption disabled for this run"
            )
            return self
        for signum in _HANDLED:
            self._prev[signum] = signal.signal(signum, self._on_signal)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for signum, prev in self._prev.items():
            try:
                signal.signal(signum, prev)
            except (ValueError, TypeError):  # interpreter teardown / exotic prev
                continue
        self._prev.clear()
        self._installed = False

    def stop_requested(self) -> bool:
        return self._flag

    @property
    def signal_name(self) -> Optional[str]:
        if self._signum is None:
            return None
        return signal.Signals(self._signum).name

    def acknowledge(self, step: int) -> None:
        """Called by the host loop when it first observes the flag: emits the
        log line + counter the signal handler could not safely emit itself."""
        get_registry().counter(
            "stoix_tpu_resilience_preemptions_total",
            "Graceful stops triggered by SIGTERM/SIGINT",
        ).inc(labels={"signal": self.signal_name or "unknown"})
        get_logger("stoix_tpu.resilience").warning(
            "[preemption] %s received — graceful stop requested at step %d: "
            "draining dispatcher, then emergency checkpoint",
            self.signal_name, step,
        )

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc_info) -> None:
        self.uninstall()
