"""Launch preflight: fail fast, with a typed error, BEFORE committing the run.

Five rounds of benchmarking never captured a chip number because a wedged
PJRT runtime hangs backend init until a blanket timeout forces CPU fallback.
The root problem is that `import jax; jax.devices()` is an unbounded bet: once
the parent process touches a wedged runtime it is stuck inside a native RPC
that no Python-level timeout can interrupt. This module keeps every risky
probe OUT of the parent (docs/DESIGN.md §2.4):

  1. **Backend probe** (`probe_backend`): a SUBPROCESS imports jax, lists
     devices, runs a small matmul, and reports platform/device-count/HBM as
     one JSON line. The parent enforces a bounded timeout and retries with
     exponential backoff; exhaustion raises `BackendUnavailableError` naming
     attempts and deadline. A wedged runtime kills the child, never the
     parent.
  2. **Config cross-validation** (`validate_config`): arch × system ×
     network × env shape checks against the probed device count, BEFORE any
     device work. ALL findings are collected into one
     `ConfigValidationError`, so one preflight run fixes the whole config.
  3. **AOT memory check** (`check_device_memory`): the compiled learner's
     `memory_analysis()` against the device's HBM `bytes_limit`; a predicted
     OOM raises `ResourcePreflightError` before the first allocation instead
     of a RESOURCE_EXHAUSTED twenty minutes into the run. Backends that
     expose no limit (CPU) degrade to an informational skip.

`run_preflight` strings the stages into a `PreflightReport` (pass/fail/skip
per stage + a one-page render) for `launcher.py --preflight-only` and CI /
SLURM prolog scripts. Everything here is opt-in via the `arch.preflight`
config block — disabled, no subprocess is spawned and the host loop is
bit-identical.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, List, NamedTuple, Optional

from stoix_tpu.observability import get_logger, get_registry
from stoix_tpu.resilience.errors import (
    BackendUnavailableError,
    ConfigValidationError,
    ResourcePreflightError,
)

# Self-contained child source: no stoix_tpu import (keeps the child cheap and
# PYTHONPATH-independent). The `backend_wedge` chaos fault is honored HERE, in
# the child, before jax is touched — simulating a PJRT runtime that accepts
# the process and then never answers — so the parent-side timeout/retry path
# is deterministically drivable (resilience/faultinject.py).
_PROBE_SOURCE = r"""
import json, os, sys, time
for entry in os.environ.get("STOIX_TPU_FAULT", "").split(","):
    if entry.strip().partition(":")[0].strip() == "backend_wedge":
        time.sleep(3600)  # wedged runtime: alive, silent, never answers
import jax
import numpy as np
devices = jax.devices()
x = jax.numpy.ones((128, 128)) @ jax.numpy.ones((128, 128))
value = float(np.asarray(x[0, 0]))
if value != 128.0:
    raise SystemExit(f"probe matmul returned {value}, expected 128.0")
stats = devices[0].memory_stats() or {}
print(json.dumps({
    "platform": devices[0].platform,
    "device_kind": getattr(devices[0], "device_kind", devices[0].platform),
    "device_count": len(devices),
    "process_count": jax.process_count(),
    "hbm_bytes_limit": stats.get("bytes_limit"),
}))
"""


class BackendProbe(NamedTuple):
    """Healthy-backend report from the subprocess probe."""

    platform: str
    device_kind: str
    device_count: int
    process_count: int
    hbm_bytes_limit: Optional[int]
    attempts: int  # attempts consumed (1 = first try answered)
    elapsed_s: float


def probe_backend(
    timeout_s: float = 60.0,
    attempts: int = 3,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 30.0,
    env: Optional[dict] = None,
) -> BackendProbe:
    """Probe the device backend in a subprocess with a bounded per-attempt
    timeout and exponential-backoff retries.

    The parent never imports jax here and never blocks past
    `attempts * timeout_s + backoffs`: a wedged runtime wedges the CHILD,
    which the timeout kills. Raises BackendUnavailableError when every
    attempt fails."""
    log = get_logger("stoix_tpu.resilience")
    counter = get_registry().counter(
        "stoix_tpu_preflight_probe_attempts_total",
        "Backend probe subprocess attempts, by outcome",
    )
    child_env = {**os.environ, **(env or {})}
    # The child only reads STOIX_TPU_FAULT; a backend_wedge armed via the
    # CONFIG spec (arch.fault_spec) must still reach it, or the chaos plan
    # logs as active while the wedge silently never fires. (When the env var
    # is set it won at configure() time, so the armed plan and the inherited
    # var already agree.)
    from stoix_tpu.resilience import faultinject

    if faultinject.backend_wedge_armed() and not child_env.get(faultinject.ENV_VAR):
        child_env[faultinject.ENV_VAR] = "backend_wedge"
    start = time.monotonic()
    last_error = "never attempted"
    for attempt in range(1, int(attempts) + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SOURCE],
                capture_output=True,
                text=True,
                timeout=float(timeout_s),
                env=child_env,
            )
        except subprocess.TimeoutExpired:
            counter.inc(labels={"outcome": "timeout"})
            last_error = f"probe timed out after {timeout_s:.0f}s (wedged backend init)"
        else:
            if proc.returncode == 0:
                for line in proc.stdout.strip().splitlines():
                    if not line.startswith("{"):
                        continue
                    payload = json.loads(line)
                    counter.inc(labels={"outcome": "ok"})
                    return BackendProbe(
                        platform=str(payload["platform"]),
                        device_kind=str(payload.get("device_kind", payload["platform"])),
                        device_count=int(payload["device_count"]),
                        process_count=int(payload.get("process_count", 1)),
                        hbm_bytes_limit=payload.get("hbm_bytes_limit"),
                        attempts=attempt,
                        elapsed_s=time.monotonic() - start,
                    )
                counter.inc(labels={"outcome": "bad_output"})
                last_error = f"probe exited 0 without a JSON report: {proc.stdout[-200:]!r}"
            else:
                counter.inc(labels={"outcome": "error"})
                tail = (proc.stderr or proc.stdout).strip().splitlines()
                last_error = (
                    f"probe exited {proc.returncode}: {tail[-1] if tail else 'no output'}"
                )
        if attempt < int(attempts):
            backoff = min(float(backoff_base_s) * (2 ** (attempt - 1)), float(backoff_max_s))
            log.warning(
                "[preflight] backend probe attempt %d/%d failed (%s) — retrying "
                "in %.1fs", attempt, attempts, last_error, backoff,
            )
            time.sleep(backoff)
    raise BackendUnavailableError(int(attempts), float(timeout_s), last_error)


def _check_mesh(findings: List[str], arch: Any, device_count: Optional[int]) -> int:
    """Resolve the mesh data-axis size (for divisibility checks below);
    appends findings for non-covering axes. Returns 1 when unresolvable."""
    axes = dict(arch.get("mesh") or {"data": -1})
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        findings.append(f"arch.mesh: at most one axis may be -1, got {axes}")
        return 1
    if device_count is not None:
        import numpy as np

        known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
        if -1 in sizes:
            if known <= 0 or device_count % known != 0:
                findings.append(
                    f"arch.mesh {axes}: fixed axes ({known}) do not divide the "
                    f"{device_count} probed devices"
                )
                return 1
            sizes[sizes.index(-1)] = device_count // known
        elif known != device_count:
            findings.append(
                f"arch.mesh {axes} covers {known} devices but the backend "
                f"probe reports {device_count}"
            )
    data = dict(zip(axes.keys(), sizes)).get("data", 1)
    return max(1, int(data) if data != -1 else 1)


def validate_config(config: Any, device_count: Optional[int] = None) -> None:
    """Cross-validate arch × system × network × env BEFORE any device work.

    `device_count` is the PROBED count (preflight must not touch jax in this
    process); None skips the device-dependent checks. Collects every finding
    and raises ONE ConfigValidationError, so a single preflight run reports
    the whole config's problems.

    Multi-process launches (JAX_COORDINATOR_ADDRESS / arch.distributed):
    the probe child sees only LOCAL devices while the mesh spans the global
    job, so the device-dependent checks are skipped — rejecting a valid
    32-device pod config against one host's 8 chips would be a preflight
    bug, not a catch."""
    findings: List[str] = []
    arch = config.get("arch") or {}
    system = config.get("system") or {}
    if device_count is not None and (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or (arch.get("distributed") or {}).get("coordinator_address")
    ):
        get_logger("stoix_tpu.resilience").info(
            "[preflight] multi-process launch configured — the probed count "
            "(%d) is host-local; skipping device-count checks", device_count,
        )
        device_count = None

    # --- arch: env/batch shape ---------------------------------------------
    total_num_envs = arch.get("total_num_envs")
    if not isinstance(total_num_envs, int) or total_num_envs <= 0:
        findings.append(f"arch.total_num_envs must be a positive int, got {total_num_envs!r}")
        total_num_envs = None
    rollout_length = system.get("rollout_length")
    if not isinstance(rollout_length, int) or rollout_length <= 0:
        findings.append(f"system.rollout_length must be a positive int, got {rollout_length!r}")
    if arch.get("total_timesteps") in (None, "~") and arch.get("num_updates") in (None, "~"):
        findings.append("set either arch.total_timesteps or arch.num_updates (both are unset)")

    is_sebulba = str(arch.get("architecture_name", "anakin")) == "sebulba"
    if is_sebulba:
        # The actor/learner/evaluator split validates through the SAME
        # mesh-role resolution the run itself uses (parallel/roles.py,
        # docs/DESIGN.md §2.11) — id ranges, non-empty primary roles, and
        # partial act/learn overlaps all surface here as findings. The
        # resolution half is jax-free by design, so this stays safe before
        # any device work; imported lazily because the parallel package
        # itself pulls in jax.
        from stoix_tpu.parallel.roles import MeshRolesError, resolve_assignments

        # The env split must be checked against the ACT role's device count —
        # the run takes actor devices from the resolved roles, so an explicit
        # arch.roles.act overriding the legacy arch.actor.device_ids must be
        # honored here too (legacy keys only as a fallback when resolution
        # itself failed or the all-devices count is unknowable pre-probe).
        n_actor_devices = None
        try:
            assignments = resolve_assignments(config, device_count=device_count)
            act = assignments.get("act")
            if act is not None:
                if act.device_ids is not None:
                    n_actor_devices = len(act.device_ids)
                elif device_count is not None:
                    n_actor_devices = device_count
        except MeshRolesError as exc:
            findings.extend(exc.findings)
        if n_actor_devices is None:
            n_actor_devices = len(list((arch.get("actor") or {}).get("device_ids") or []))
        actors_per_device = int((arch.get("actor") or {}).get("actor_per_device", 1) or 1)
        num_actors = max(1, n_actor_devices) * max(1, actors_per_device)
        if total_num_envs is not None and total_num_envs % num_actors != 0:
            findings.append(
                f"arch.total_num_envs ({total_num_envs}) must be divisible by "
                f"num_actors ({n_actor_devices} device(s) x {actors_per_device} "
                f"actor(s)/device = {num_actors})"
            )
    else:
        data_shards = _check_mesh(findings, arch, device_count)
        update_batch_size = int(arch.get("update_batch_size", 1) or 1)
        if update_batch_size <= 0:
            findings.append(
                f"arch.update_batch_size must be positive, got {update_batch_size}"
            )
            update_batch_size = 1
        divisor = data_shards * update_batch_size
        if total_num_envs is not None and total_num_envs % divisor != 0:
            findings.append(
                f"arch.total_num_envs ({total_num_envs}) must be divisible by "
                f"data_shards * update_batch_size ({data_shards} * {update_batch_size})"
            )
        # PPO-family minibatching: the per-shard batch must split evenly.
        num_minibatches = system.get("num_minibatches")
        if (
            isinstance(num_minibatches, int)
            and num_minibatches > 0
            and total_num_envs is not None
            and isinstance(rollout_length, int)
            and rollout_length > 0
        ):
            per_shard = (rollout_length * total_num_envs) // divisor
            if per_shard % num_minibatches != 0:
                findings.append(
                    f"per-shard batch (rollout_length * envs_per_shard = {per_shard}) "
                    f"not divisible by system.num_minibatches ({num_minibatches})"
                )

    # --- system: guard mode / fault spec parse early, not mid-run ----------
    from stoix_tpu.resilience import faultinject, guards

    try:
        guards.resolve_mode(config)
    except ValueError as exc:
        findings.append(str(exc))
    try:
        faultinject.parse_spec(arch.get("fault_spec"))
    except ValueError as exc:
        findings.append(f"arch.fault_spec: {exc}")

    # --- env: the scenario must resolve to a registered constructor --------
    env_cfg = config.get("env") or {}
    scenario = env_cfg.get("scenario")
    scenario_name = scenario.get("name") if isinstance(scenario, dict) else scenario
    # Adapter-backed env groups (cvec pools, envpool, gymnasium) resolve their
    # ids against external catalogs — only first-party JAX suites are checked.
    first_party = str(env_cfg.get("env_name", "")) not in (
        "cvec", "envpool", "gymnasium",
    )
    if scenario_name and first_party:
        try:
            from stoix_tpu.envs.registry import ENV_REGISTRY

            if str(scenario_name) not in ENV_REGISTRY:
                findings.append(
                    f"env scenario '{scenario_name}' not in the first-party "
                    f"registry (known: {sorted(ENV_REGISTRY)}); a typo here "
                    f"otherwise surfaces as a KeyError after backend init"
                )
        except Exception as exc:  # noqa: BLE001 — registry probing is best-effort
            get_logger("stoix_tpu.resilience").info(
                "[preflight] env registry check skipped (%s)", exc
            )

    # --- network: layer sizes must be positive ints ------------------------
    network = config.get("network") or {}
    for net_name, net in network.items():
        if not isinstance(net, dict):
            continue
        for part_name, part in net.items():
            if not isinstance(part, dict):
                continue
            sizes = part.get("layer_sizes")
            if sizes is not None and (
                not isinstance(sizes, (list, tuple))
                or any(not isinstance(s, int) or s <= 0 for s in sizes)
            ):
                findings.append(
                    f"network.{net_name}.{part_name}.layer_sizes must be positive "
                    f"ints, got {sizes!r}"
                )

    if findings:
        raise ConfigValidationError(findings)


def estimate_compiled_memory(compiled: Any) -> Optional[dict]:
    """Predicted device-memory footprint of a compiled XLA executable, from
    `compiled.memory_analysis()`; None when the object is not a compiled
    executable or the backend exposes no analysis (then there is nothing to
    gate on)."""
    analysis = getattr(compiled, "memory_analysis", None)
    if analysis is None:
        return None
    try:
        stats = analysis()
    except Exception:  # noqa: BLE001 — absent analysis is a skip, not a failure
        return None
    if stats is None:
        return None
    fields = {}
    for name in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        value = getattr(stats, name, None)
        if value is not None:
            fields[name] = int(value)
    if not fields:
        return None
    # Aliased bytes (donated buffers) are counted in both arguments and
    # outputs but occupy HBM once.
    total = (
        fields.get("argument_size_in_bytes", 0)
        + fields.get("output_size_in_bytes", 0)
        + fields.get("temp_size_in_bytes", 0)
        + fields.get("generated_code_size_in_bytes", 0)
        - fields.get("alias_size_in_bytes", 0)
    )
    return {"predicted_bytes": max(0, total), **fields}


def check_device_memory(
    compiled: Any,
    headroom: float = 0.9,
    device: Any = None,
) -> Optional[dict]:
    """Gate a compiled learner on predicted HBM: raises ResourcePreflightError
    when memory_analysis predicts more than `headroom` of the device's
    bytes_limit. Returns the estimate dict (with 'limit_bytes' when known), or
    None when the backend exposes no analysis. CPU (no bytes_limit) logs the
    estimate and passes — there is no HBM to protect."""
    estimate = estimate_compiled_memory(compiled)
    if estimate is None:
        return None
    log = get_logger("stoix_tpu.resilience")
    if device is None:
        import jax

        device = jax.devices()[0]
    try:
        stats = device.memory_stats() or {}
    except Exception:  # noqa: BLE001 — CPU/older PJRT: no stats, nothing to gate
        stats = {}
    limit = stats.get("bytes_limit")
    gib = 1024.0 ** 3
    if not limit:
        log.info(
            "[preflight] predicted program memory %.2f GiB (device exposes no "
            "bytes_limit — HBM gate skipped)", estimate["predicted_bytes"] / gib,
        )
        return estimate
    estimate["limit_bytes"] = int(limit)
    get_registry().gauge(
        "stoix_tpu_preflight_predicted_memory_bytes",
        "memory_analysis() prediction for the compiled learner step",
    ).set(float(estimate["predicted_bytes"]))
    if estimate["predicted_bytes"] > float(headroom) * float(limit):
        raise ResourcePreflightError(
            estimate["predicted_bytes"],
            int(limit),
            float(headroom),
            getattr(device, "device_kind", getattr(device, "platform", "device")),
            detail=f"temp={estimate.get('temp_size_in_bytes', 0) / gib:.2f} GiB, "
            f"args={estimate.get('argument_size_in_bytes', 0) / gib:.2f} GiB",
        )
    log.info(
        "[preflight] predicted program memory %.2f GiB fits %.0f%% of %.2f GiB HBM",
        estimate["predicted_bytes"] / gib, headroom * 100, limit / gib,
    )
    return estimate


class PreflightSettings(NamedTuple):
    """Resolved `arch.preflight` block (all knobs with defaults applied)."""

    enabled: bool
    probe_timeout_s: float
    probe_attempts: int
    probe_backoff_base_s: float
    probe_backoff_max_s: float
    hbm_headroom: float
    compile_deadline_s: float
    first_window_deadline_s: float
    hard_exit_grace_s: float


def settings_from_config(config: Any) -> PreflightSettings:
    cfg = (config.get("arch") or {}).get("preflight") or {}
    return PreflightSettings(
        enabled=bool(cfg.get("enabled", False)),
        probe_timeout_s=float(cfg.get("probe_timeout_s", 60.0)),
        probe_attempts=int(cfg.get("probe_attempts", 3)),
        probe_backoff_base_s=float(cfg.get("probe_backoff_base_s", 1.0)),
        probe_backoff_max_s=float(cfg.get("probe_backoff_max_s", 30.0)),
        hbm_headroom=float(cfg.get("hbm_headroom", 0.9)),
        compile_deadline_s=float(cfg.get("compile_deadline_s", 1800.0)),
        first_window_deadline_s=float(cfg.get("first_window_deadline_s", 900.0)),
        hard_exit_grace_s=float(cfg.get("hard_exit_grace_s", 0.0)),
    )


class PreflightReport:
    """Stage-by-stage preflight outcome: (name, status, detail) rows where
    status is 'pass' | 'fail' | 'skip'. `ok` ignores skips; `render()` is the
    one-page text `launcher.py --preflight-only` prints for CI/prolog logs."""

    def __init__(self) -> None:
        self.stages: List[tuple] = []

    def add(self, name: str, status: str, detail: str = "") -> None:
        assert status in ("pass", "fail", "skip"), status
        self.stages.append((name, status, detail))

    @property
    def ok(self) -> bool:
        return all(status != "fail" for _name, status, _detail in self.stages)

    def render(self) -> str:
        mark = {"pass": "PASS", "fail": "FAIL", "skip": "skip"}
        width = max((len(n) for n, _s, _d in self.stages), default=8)
        lines = ["stoix_tpu preflight report", "=" * 40]
        for name, status, detail in self.stages:
            lines.append(f"{name.ljust(width)}  [{mark[status]}]  {detail}".rstrip())
        lines.append("=" * 40)
        lines.append(f"overall: {'PASS' if self.ok else 'FAIL'}")
        return "\n".join(lines)


def run_preflight(
    configs: Any = None,
    settings: Optional[PreflightSettings] = None,
) -> PreflightReport:
    """Probe the backend, then cross-validate each config against the probed
    topology. `configs` is one config, a list of (label, config) pairs, or
    None (probe only). Stages that cannot run (probe dead -> no device count;
    no configs) record as skip/fail rather than aborting the report."""
    settings = settings or PreflightSettings(
        True, 60.0, 3, 1.0, 30.0, 0.9, 1800.0, 900.0, 0.0
    )
    report = PreflightReport()
    device_count: Optional[int] = None
    try:
        probe = probe_backend(
            timeout_s=settings.probe_timeout_s,
            attempts=settings.probe_attempts,
            backoff_base_s=settings.probe_backoff_base_s,
            backoff_max_s=settings.probe_backoff_max_s,
        )
        device_count = probe.device_count
        report.add(
            "backend_probe", "pass",
            f"{probe.platform} x{probe.device_count} ({probe.device_kind}), "
            f"attempt {probe.attempts}, {probe.elapsed_s:.1f}s",
        )
    except BackendUnavailableError as exc:
        report.add("backend_probe", "fail", str(exc))

    if configs is None:
        report.add("config_validation", "skip", "no configs supplied")
        return report
    pairs = configs if isinstance(configs, list) else [("config", configs)]
    for label, config in pairs:
        try:
            validate_config(config, device_count=device_count)
            report.add(f"config[{label}]", "pass", "arch/system/network/env cross-checks")
        except ConfigValidationError as exc:
            report.add(f"config[{label}]", "fail", "; ".join(exc.findings))
    return report
