"""Typed failure vocabulary for the fault-tolerance layer (docs/DESIGN.md §2.3).

Every recovery path in stoix_tpu/resilience raises (or propagates) one of
these instead of an anonymous RuntimeError/queue.Empty/orbax exception, so
callers — and the fault-injection test-suite — can assert on the FAILURE
CLASS, not on message strings. This module imports nothing from the rest of
the package; it is safe to import from utils/, sebulba/, and systems/ without
creating cycles.
"""

from __future__ import annotations

from typing import Optional


class DivergenceError(RuntimeError):
    """Raised on the host by `system.update_guard=halt` when the in-jit guard
    flags a non-finite loss or global grad-norm. Names the step, the loss,
    and the offending metric so the operator knows WHAT diverged, not just
    that something did."""

    def __init__(self, step: int, loss: float, grad_norm: float, metric: str):
        self.step = int(step)
        self.loss = float(loss)
        self.grad_norm = float(grad_norm)
        self.metric = metric
        super().__init__(
            f"learner diverged at step {self.step}: non-finite {metric} "
            f"(loss={self.loss}, grad_norm={self.grad_norm}); the guarded "
            f"update was NOT applied (update_guard=halt). Re-run with "
            f"system.update_guard=skip to drop bad updates instead of halting."
        )


class ComponentFailure(RuntimeError):
    """Poison-pill for Sebulba: a component (actor thread, evaluator) failed
    unrecoverably. Propagated through OnPolicyPipeline/ParameterServer queues
    so the peer FAILS FAST on its next get instead of burning a full collect
    timeout against a dead producer."""

    def __init__(self, component: str, reason: str, cause: Optional[BaseException] = None):
        self.component = component
        self.reason = reason
        self.__cause__ = cause
        detail = f": {type(cause).__name__}: {cause}" if cause is not None else ""
        super().__init__(f"{component} failed unrecoverably ({reason}){detail}")


class EvaluatorStallError(RuntimeError):
    """AsyncEvaluator.wait_until_idle timed out: evaluation work is still
    in flight (or wedged) at shutdown. Carries the evaluator's last-heartbeat
    age so the caller can tell slow-but-alive from dead."""

    def __init__(self, timeout: float, heartbeat_age: Optional[float], pending: int):
        self.timeout = float(timeout)
        self.heartbeat_age = heartbeat_age
        self.pending = int(pending)
        age = (
            "never completed an evaluation"
            if heartbeat_age is None
            else f"last finished one {heartbeat_age:.1f}s ago"
        )
        super().__init__(
            f"async evaluator still busy after {timeout:.0f}s "
            f"({pending} request(s) queued; {age}) — shutdown would drop "
            f"in-flight evaluation work"
        )


class CheckpointIntegrityError(RuntimeError):
    """A restored checkpoint failed validation. `kind` names the distinct
    rejection class — 'structure' (tree/leaf/dtype mismatch), 'non_finite'
    (NaN/inf where the template is finite), or 'digest' (on-disk bytes no
    longer match the per-leaf sha256 manifest recorded at save time:
    bit-rot, docs/DESIGN.md §2.9) — so the fallback walk's log and
    `Checkpointer.last_restore_report` carry typed reasons, not prose.
    Restore falls back to the newest VALID checkpoint when one exists; this
    error surfaces only when no candidate passes."""

    def __init__(self, step: int, reason: str, kind: str = "structure"):
        self.step = int(step)
        self.reason = reason
        self.kind = str(kind)
        super().__init__(
            f"checkpoint at step {step} failed integrity validation "
            f"[{self.kind}]: {reason}"
        )


class StateCorruptionError(RuntimeError):
    """The state-integrity sentinel (resilience/integrity.py, docs/DESIGN.md
    §2.9) proved silent state corruption: either the per-device replica
    fingerprints of a replicated state group disagree (`kind=
    'replica_mismatch'` — an HBM bit-flip or a wrong-math core broke the
    post-pmean bit-identity invariant; names the deviating device(s) and
    process(es)), or the determinism probe's replay of a recorded
    (state, minibatch) pair through the learn step no longer matches its
    recorded output fingerprint (`kind='determinism'` — wrong math even at
    replica count 1). The values involved are FINITE — no divergence guard
    or finiteness check can see this class. The handling path records the
    offender in the quarantine file and exits with
    integrity.EXIT_CODE_STATE_CORRUPTION (88) so a supervising launcher
    restores the newest digest-verified checkpoint."""

    def __init__(
        self,
        kind: str,
        groups: list,
        devices: list,
        processes: list,
        window: int,
        step: int,
        detail: str = "",
    ):
        self.kind = str(kind)
        self.groups = [str(g) for g in groups]
        self.devices = [int(d) for d in devices]
        self.processes = sorted(int(p) for p in processes)
        self.window = int(window)
        self.step = int(step)
        self.detail = detail
        if self.kind == "determinism":
            what = (
                f"learn-step replay diverged from its recorded fingerprint "
                f"for state group(s) {', '.join(self.groups)} — the same "
                f"compiled program on the same input computed a different "
                f"answer (wrong-math core)"
            )
        else:
            names = ", ".join(f"device {d}" for d in self.devices) or "unknown device"
            procs = ", ".join(f"process {p}" for p in self.processes)
            what = (
                f"replica fingerprints of state group(s) "
                f"{', '.join(self.groups)} diverge at window {self.window} "
                f"(step {self.step}): {names} (on {procs}) disagree(s) with "
                f"the fleet majority — the post-pmean bit-identity invariant "
                f"is broken (HBM bit-flip or wrong-math core)"
            )
        super().__init__(
            f"silent state corruption detected: {what}"
            f"{(' — ' + detail) if detail else ''}. Recovery: restore the "
            f"newest digest-verified checkpoint and quarantine the offending "
            f"host (launcher.py --supervise relaunches on exit code 88)."
        )


class PreflightError(RuntimeError):
    """Base class for launch-hardening failures (resilience/preflight.py,
    docs/DESIGN.md §2.4): the run was aborted BEFORE (or during) its first
    window by a preflight check or watchdog, with a typed cause — never by an
    indefinite hang or an anonymous 20-minutes-later OOM."""


class BackendUnavailableError(PreflightError):
    """The subprocess-isolated backend probe never got a healthy answer from
    the device runtime: every attempt timed out (wedged PJRT init) or errored.
    Names the attempt count and the per-attempt deadline so the operator can
    tell 'chip wedged after N retries' from a config mistake."""

    def __init__(self, attempts: int, timeout_s: float, last_error: str):
        self.attempts = int(attempts)
        self.timeout_s = float(timeout_s)
        self.last_error = last_error
        super().__init__(
            f"device backend unavailable: {attempts} probe attempt(s) failed "
            f"({timeout_s:.0f}s deadline each); last failure: {last_error}. "
            f"The probe runs in a SUBPROCESS, so the wedged runtime never "
            f"touched this process — safe to retry or fall back."
        )


class ConfigValidationError(PreflightError):
    """Config cross-validation (arch × system × network × env) failed before
    any device work. Carries ALL findings, not just the first, so one preflight
    run fixes the whole config."""

    def __init__(self, findings: list):
        self.findings = list(findings)
        lines = "\n".join(f"  - {f}" for f in self.findings)
        super().__init__(
            f"config validation failed with {len(self.findings)} finding(s):\n{lines}"
        )


class ResourcePreflightError(PreflightError):
    """XLA's post-compile memory_analysis predicts this program cannot fit the
    device: predicted bytes exceed the HBM budget (bytes_limit × headroom).
    Aborting here costs seconds; discovering it as a runtime OOM costs the
    whole compile plus a cryptic RESOURCE_EXHAUSTED mid-run."""

    def __init__(self, predicted_bytes: int, limit_bytes: int, headroom: float,
                 device_kind: str, detail: str = ""):
        self.predicted_bytes = int(predicted_bytes)
        self.limit_bytes = int(limit_bytes)
        self.headroom = float(headroom)
        self.device_kind = device_kind
        gib = 1024.0 ** 3
        super().__init__(
            f"predicted device memory {predicted_bytes / gib:.2f} GiB exceeds "
            f"{headroom:.0%} of the {limit_bytes / gib:.2f} GiB HBM on "
            f"{device_kind}{(' (' + detail + ')') if detail else ''} — shrink "
            f"arch.total_num_envs / system.rollout_length / the network, or "
            f"raise arch.preflight.hbm_headroom if the estimate is known-loose"
        )


class CompileStallError(PreflightError):
    """A watchdog deadline expired around first-compile or first-window
    execution (resilience/watchdog.py). Carries the stage name, the deadline,
    and the all-thread stack dump taken at expiry, so a wedged backend leaves
    a diagnosis instead of an indefinite hang."""

    def __init__(self, stage: str, deadline_s: float, dump: Optional[str] = None):
        self.stage = stage
        self.deadline_s = float(deadline_s)
        self.dump = dump
        knob = (
            "compile_deadline_s"
            if "compile" in stage
            else "first_window_deadline_s"
        )
        super().__init__(
            f"'{stage}' exceeded its {deadline_s:.0f}s watchdog deadline — "
            f"backend likely wedged (thread stacks + registry snapshot were "
            f"dumped to the stoix_tpu.resilience log). Raise "
            f"arch.preflight.{knob} if this shape legitimately "
            f"compiles/executes slower."
        )


class FleetError(RuntimeError):
    """Base class for cross-host fleet-coordination failures
    (resilience/fleet.py, docs/DESIGN.md §2.6): a multi-host SPMD run lost a
    peer, a cross-host barrier blew its deadline, or agreement could not be
    reached — typed, so the launcher's supervision loop and the e2e tests can
    branch on the failure CLASS instead of scraping a hung collective."""


class FleetPartitionError(FleetError):
    """A peer process stopped heartbeating (or never answered an agreement
    vote) past the configured deadline: the fleet is partitioned and every
    pending collective would hang forever. Names the missing process(es) so
    the operator knows WHICH host died. The handling path writes a
    local-shard emergency checkpoint and exits with
    fleet.EXIT_CODE_FLEET_PARTITION so a supervising launcher can relaunch at
    the surviving topology."""

    def __init__(self, missing_processes: list, deadline_s: float, detail: str = ""):
        self.missing_processes = sorted(int(p) for p in missing_processes)
        self.deadline_s = float(deadline_s)
        self.detail = detail
        names = ", ".join(f"process {p}" for p in self.missing_processes) or "unknown peer"
        super().__init__(
            f"fleet partition: {names} silent past the {deadline_s:.0f}s "
            f"deadline{(' (' + detail + ')') if detail else ''} — every "
            f"cross-host collective would hang; writing a local-shard "
            f"emergency checkpoint and exiting with the fleet exit code so a "
            f"supervisor can relaunch at the surviving topology"
        )


class FleetBarrierTimeout(FleetError):
    """A cross-host barrier (fleet.guarded_barrier) exceeded its deadline:
    at least one peer never arrived. Carries the barrier name, the deadline,
    and the watchdog's all-thread stack dump taken at expiry."""

    def __init__(self, barrier: str, deadline_s: float, dump: Optional[str] = None):
        self.barrier = barrier
        self.deadline_s = float(deadline_s)
        self.dump = dump
        super().__init__(
            f"fleet barrier '{barrier}' not released within its "
            f"{deadline_s:.0f}s deadline — a peer never arrived (dead host or "
            f"wedged collective); thread stacks were dumped to the "
            f"stoix_tpu.resilience log. Raise arch.fleet.barrier_deadline_s "
            f"if this barrier legitimately takes longer."
        )


class InjectedFault(RuntimeError):
    """Raised by the fault-injection harness (resilience/faultinject.py) at an
    armed injection point. Distinct from real failures so supervision tests
    can assert the recovery path fired on THIS fault and not a genuine bug."""
