"""Typed failure vocabulary for the fault-tolerance layer (docs/DESIGN.md §2.3).

Every recovery path in stoix_tpu/resilience raises (or propagates) one of
these instead of an anonymous RuntimeError/queue.Empty/orbax exception, so
callers — and the fault-injection test-suite — can assert on the FAILURE
CLASS, not on message strings. This module imports nothing from the rest of
the package; it is safe to import from utils/, sebulba/, and systems/ without
creating cycles.
"""

from __future__ import annotations

from typing import Optional


class DivergenceError(RuntimeError):
    """Raised on the host by `system.update_guard=halt` when the in-jit guard
    flags a non-finite loss or global grad-norm. Names the step, the loss,
    and the offending metric so the operator knows WHAT diverged, not just
    that something did."""

    def __init__(self, step: int, loss: float, grad_norm: float, metric: str):
        self.step = int(step)
        self.loss = float(loss)
        self.grad_norm = float(grad_norm)
        self.metric = metric
        super().__init__(
            f"learner diverged at step {self.step}: non-finite {metric} "
            f"(loss={self.loss}, grad_norm={self.grad_norm}); the guarded "
            f"update was NOT applied (update_guard=halt). Re-run with "
            f"system.update_guard=skip to drop bad updates instead of halting."
        )


class ComponentFailure(RuntimeError):
    """Poison-pill for Sebulba: a component (actor thread, evaluator) failed
    unrecoverably. Propagated through OnPolicyPipeline/ParameterServer queues
    so the peer FAILS FAST on its next get instead of burning a full collect
    timeout against a dead producer."""

    def __init__(self, component: str, reason: str, cause: Optional[BaseException] = None):
        self.component = component
        self.reason = reason
        self.__cause__ = cause
        detail = f": {type(cause).__name__}: {cause}" if cause is not None else ""
        super().__init__(f"{component} failed unrecoverably ({reason}){detail}")


class EvaluatorStallError(RuntimeError):
    """AsyncEvaluator.wait_until_idle timed out: evaluation work is still
    in flight (or wedged) at shutdown. Carries the evaluator's last-heartbeat
    age so the caller can tell slow-but-alive from dead."""

    def __init__(self, timeout: float, heartbeat_age: Optional[float], pending: int):
        self.timeout = float(timeout)
        self.heartbeat_age = heartbeat_age
        self.pending = int(pending)
        age = (
            "never completed an evaluation"
            if heartbeat_age is None
            else f"last finished one {heartbeat_age:.1f}s ago"
        )
        super().__init__(
            f"async evaluator still busy after {timeout:.0f}s "
            f"({pending} request(s) queued; {age}) — shutdown would drop "
            f"in-flight evaluation work"
        )


class CheckpointIntegrityError(RuntimeError):
    """A restored checkpoint failed validation (tree-structure mismatch or a
    non-finite value where the template is finite). Restore falls back to the
    newest VALID checkpoint when one exists; this error surfaces only when no
    candidate passes."""

    def __init__(self, step: int, reason: str):
        self.step = int(step)
        self.reason = reason
        super().__init__(f"checkpoint at step {step} failed integrity validation: {reason}")


class InjectedFault(RuntimeError):
    """Raised by the fault-injection harness (resilience/faultinject.py) at an
    armed injection point. Distinct from real failures so supervision tests
    can assert the recovery path fired on THIS fault and not a genuine bug."""
