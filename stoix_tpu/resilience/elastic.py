"""Topology-elastic resize protocol (docs/DESIGN.md §2.14).

Production TPU allocations shrink and grow under preemption; before this
module a partition meant rescue + relaunch at a FIXED topology — the one
failure mode the Podracer layout assumes away. This module is the protocol
half of "resize instead of die":

  * **The resize request** (`resize_request.json`, written next to the fleet
    emergency store): a deliberate hand-off from a dying incarnation to the
    supervising launcher, naming the action (shrink/grow), the device counts
    on both sides, and the exact config overrides the relaunch needs
    (re-derived mesh axes + population re-placement). Written by
    `resize_exit` together with the emergency snapshot and a schema-valid
    flight record, then the process hard-exits `EXIT_CODE_ELASTIC_RESIZE`
    (89) — distinguishable from a partition (87) in supervisor logs.
  * **Topology re-derivation** (`topology_overrides` / `survivor_overrides`):
    `arch.mesh` axes are re-derived for the devices actually present via
    `roles.elastic_mesh_axes` and validated through
    `roles.resolve_assignments` — never replayed from the dead topology.
    Explicit `arch.roles` device ids that no longer fit fall back to
    role re-derivation (`arch.roles=~`). Pure host logic, no jax import:
    the supervising launcher computes the survivor topology before spawning.
  * **The relaunch policy** lives in `launcher.py --supervise --elastic`:
    rc 89 consumes the resize request and relaunches at the requested
    topology with the emergency restore overrides; rc 87 re-probes the
    backend and relaunches at whatever survived. `--elastic` off is pinned
    bit-identical to the fixed-topology supervision this replaces.

The state half — re-placing PBT members across a different P — is
`stoix_tpu/population/elastic.py`, wired through `AnakinSetup
.restore_transform` into `fleet.restore_emergency`'s raw-transform seam.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from stoix_tpu.observability import flightrec, get_logger
from stoix_tpu.parallel import roles as roles_lib
from stoix_tpu.resilience.exit_codes import EXIT_CODE_ELASTIC_RESIZE

RESIZE_REQUEST_NAME = "resize_request.json"

RESIZE_ACTIONS = ("shrink", "grow")


class ElasticResizeError(ValueError):
    """A resize that cannot be satisfied (below one device, bad action,
    un-rescalable mesh axes)."""


def plan_resize(action: str, device_count: int) -> int:
    """The target device count for a resize fault: shrink halves, grow
    doubles — the preemption granularity of slice-sized allocations. Refuses
    a shrink below one device with the typed error (the run should die as a
    plain failure, not loop relaunching an impossible topology)."""
    if action not in RESIZE_ACTIONS:
        raise ElasticResizeError(
            f"unknown resize action {action!r}; known: {', '.join(RESIZE_ACTIONS)}"
        )
    if device_count < 1:
        raise ElasticResizeError(
            f"cannot resize from {device_count} device(s)"
        )
    if action == "shrink":
        target = device_count // 2
        if target < 1:
            raise ElasticResizeError(
                f"cannot shrink below one device (currently {device_count})"
            )
        return target
    return device_count * 2


def topology_overrides(config: Any, device_count: int) -> List[str]:
    """Config overrides that re-derive the mesh for `device_count` devices
    via `roles.elastic_mesh_axes`, validated through
    `roles.resolve_assignments` against the new count. When explicit
    `arch.roles` device ids no longer fit the survivors, the roles block is
    dropped (`arch.roles=~`) so assignment re-derives from the architecture
    name instead of replaying the dead topology. Jax-free host logic."""
    arch = dict((config.get("arch") if config is not None else None) or {})
    axes = roles_lib.elastic_mesh_axes(
        dict(arch.get("mesh") or {"data": -1}), device_count
    )
    candidate: Dict[str, Any] = {
        "arch": {
            "architecture_name": arch.get("architecture_name", "anakin"),
            "mesh": dict(axes),
            "roles": arch.get("roles"),
        }
    }
    overrides: List[str] = []
    try:
        roles_lib.resolve_assignments(candidate, device_count=device_count)
    except roles_lib.MeshRolesError:
        # Explicit role assignments pin device ids from the old topology;
        # re-derive instead. If even the derived assignment cannot fit, the
        # error propagates — an impossible topology must refuse, not relaunch.
        candidate["arch"]["roles"] = None
        roles_lib.resolve_assignments(candidate, device_count=device_count)
        overrides.append("arch.roles=~")
    overrides.extend(f"arch.mesh.{name}={size}" for name, size in axes.items())
    return overrides


def survivor_overrides(
    device_count: int, overrides: Optional[List[str]] = None
) -> List[str]:
    """The rc-87 elastic path's topology re-derivation, for the supervising
    launcher (which holds no composed config — only the job's override list).
    Any `arch.mesh.*=` / `arch.roles=` overrides already on the job are
    parsed into a minimal config so the re-derivation starts from what the
    dead incarnation actually ran with."""
    axes: Dict[str, int] = {}
    explicit_roles = False
    for entry in overrides or []:
        key, _, value = str(entry).partition("=")
        if key.startswith("arch.mesh."):
            try:
                axes[key[len("arch.mesh."):]] = int(value)
            except ValueError:
                continue
        elif key == "arch.roles" and value not in ("~", "null", ""):
            explicit_roles = True
    config = {"arch": {"mesh": axes or None, "roles": None}}
    derived = topology_overrides(config, device_count)
    if explicit_roles and "arch.roles=~" not in derived:
        derived.insert(0, "arch.roles=~")
    return derived


def write_resize_request(
    directory: str,
    *,
    action: str,
    from_devices: int,
    target_devices: int,
    window: int,
    step: int,
    platform: str,
    overrides: Optional[List[str]] = None,
) -> str:
    """Atomically write the resize hand-off next to the emergency store;
    returns the request path."""
    os.makedirs(directory, exist_ok=True)
    request = {
        "format": 1,
        "action": str(action),
        "from_devices": int(from_devices),
        "target_devices": int(target_devices),
        "window": int(window),
        "step": int(step),
        "platform": str(platform),
        "overrides": list(overrides or []),
        "unix_time": time.time(),
    }
    path = os.path.join(directory, RESIZE_REQUEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(request, f, indent=1)
    os.replace(tmp, path)
    return path


def read_resize_request(directory: str) -> Optional[Dict[str, Any]]:
    """The pending resize request under `directory`, or None."""
    try:
        with open(os.path.join(str(directory), RESIZE_REQUEST_NAME)) as f:
            request = json.load(f)
    except (OSError, ValueError):
        return None
    return request if isinstance(request, dict) else None


def consume_resize_request(directory: str) -> Optional[Dict[str, Any]]:
    """One-shot read for the supervising launcher: the request is removed so
    a LATER rc-89 (the grow leg of a soak cycle) is always answered by ITS
    OWN request, never a stale one."""
    request = read_resize_request(directory)
    if request is not None:
        try:
            os.remove(os.path.join(str(directory), RESIZE_REQUEST_NAME))
        except OSError:
            pass
    return request


def resize_overrides(config: Any, target_devices: int) -> List[str]:
    """Everything a relaunch at `target_devices` needs beyond the restore
    overrides: re-derived mesh axes, plus — for population runs — the
    population re-placement overrides (`arch.population.size` scaled with
    the device ratio, docs/DESIGN.md §2.14)."""
    overrides = topology_overrides(config, target_devices)
    arch = dict((config.get("arch") if config is not None else None) or {})
    pop_cfg = dict(arch.get("population") or {})
    if int(pop_cfg.get("size", 1) or 1) > 1:
        # Lazy import: population code pulls jax; the protocol half must stay
        # importable from a supervisor/CI process without an accelerator.
        from stoix_tpu.population import elastic as population_elastic

        overrides.extend(
            population_elastic.population_resize_overrides(
                config, target_devices=target_devices
            )
        )
    return overrides


def resize_exit(
    action: str,
    *,
    config: Any,
    window_idx: int,
    step: int,
    fleet_coord: Any = None,
) -> None:
    """The rc-89 exit protocol (never returns): secure the emergency
    snapshot, write the resize request naming the target topology + relaunch
    overrides, dump a schema-valid flight record, hard-exit 89. Ordering
    matters — the snapshot and both artifacts must be on disk before the
    exit, because `os._exit` runs no finally blocks."""
    import jax

    log = get_logger("stoix_tpu.resilience")
    from_devices = jax.device_count()
    target_devices = plan_resize(action, from_devices)
    overrides = resize_overrides(config, target_devices)
    emergency_dir = str(
        dict(dict(config.get("arch") or {}).get("fleet") or {}).get(
            "emergency_dir", os.path.join("checkpoints", "fleet_emergency")
        )
    )
    if fleet_coord is not None:
        try:
            saved = fleet_coord.emergency_save()
        except Exception as exc:  # noqa: STX003 — the resize hand-off must still be written when the rescue save fails; the relaunch then restores the newest digest-verified orbax store instead
            saved = None
            log.error("[elastic] emergency save failed: %s", exc)
        if saved is None:
            log.warning(
                "[elastic] no rescue snapshot secured — the relaunch will "
                "restore the newest digest-verified checkpoint instead"
            )
    else:
        log.warning(
            "[elastic] resize without a fleet coordinator (arch.fleet."
            "enabled=false): no emergency snapshot — the relaunch restores "
            "the newest digest-verified checkpoint"
        )
    request_path = write_resize_request(
        emergency_dir,
        action=action,
        from_devices=from_devices,
        target_devices=target_devices,
        window=window_idx,
        step=step,
        platform=str(jax.default_backend()),
        overrides=overrides,
    )
    reason = (
        f"elastic {action}: {from_devices} -> {target_devices} device(s) "
        f"at window {window_idx} (step {step})"
    )
    log.warning(
        "[elastic] %s — request at %s, exiting %d for the elastic supervisor",
        reason, request_path, EXIT_CODE_ELASTIC_RESIZE,
    )
    flightrec.get_flight_recorder().record(
        "elastic_resize",
        action=action,
        window=window_idx,
        step=step,
        from_devices=from_devices,
        target_devices=target_devices,
    )
    flightrec.dump_flight_record(
        emergency_dir, reason=reason, exit_code=EXIT_CODE_ELASTIC_RESIZE
    )
    sys.stderr.flush()
    os._exit(EXIT_CODE_ELASTIC_RESIZE)
