"""Cross-host fleet coordination for multi-host SPMD runs (docs/DESIGN.md §2.6).

Every resilience mechanism from PRs 3-4 (PreemptionHandler, divergence
guards, watchdogs, emergency checkpoint) is strictly per-process, but the
canonical MULTI-HOST failures are collective: one preempted host that drains
and checkpoints alone leaves its peers hanging forever in the next
all-reduce, and a host that dies outright turns the whole pod into a silent
infinite collective until the scheduler kills it. This module is the
cross-host net, built on the `jax.distributed` key-value store when a
multi-process runtime is live — with an injectable in-process fake
(`FakeFleetStore`) so every path is unit-testable without spawning
processes. Four pillars:

  * **Agreed stop decisions** — per-host preemption/fault flags are combined
    at each eval-window boundary so ALL hosts drain, emergency-checkpoint,
    and exit at the SAME window: never a torn checkpoint, never a
    one-host-exits-while-peers-hang-in-pmean. Two transports share one
    decision rule (`FleetDecision`): the Anakin runner piggybacks a tiny
    per-device payload (`telemetry_for_fetch`: stop-flag byte + window
    wall-time) on its existing coalesced metric fetch — zero extra
    collectives — while Sebulba exchanges window-indexed votes through the
    KV store (`agree_at_window`).
  * **Fleet heartbeat + partition detection** — each host publishes a
    heartbeat sequence number off the hot path; a monitor thread converts a
    stale peer into a typed `FleetPartitionError` naming the missing
    process, writes the local-shard emergency checkpoint, interrupts the
    main thread (which may be wedged inside a dead collective), and — after
    `exit_grace_s` — hard-exits with `EXIT_CODE_FLEET_PARTITION` so the
    supervising launcher can relaunch at the surviving topology.
  * **Straggler skew telemetry** — per-host window wall-times are exchanged
    via `process_allgather` and exported as `stoix_tpu_fleet_*` gauges; a
    host slower than `skew_warn_ratio` x the fastest raises a typed
    `FleetStragglerWarning`.
  * **Deadline-guarded barriers** — `guarded_barrier` wraps cross-host
    barriers in the PR 4 `Watchdog` stage machinery with a
    `FleetBarrierTimeout` error factory, so a peer that never arrives leaves
    a stack dump and a typed error instead of an indefinite hang.

The local-shard emergency checkpoint (`emergency_save`) is the partition
path's answer to "orbax saves are collective, and my peer is dead": each
window the runner stages an on-device snapshot COPY of the learner state and
promotes it to "confirmed" once that window's metrics materialize (stream
ordering proves the producing programs completed, so reading the copy can
never block on a dead peer's collective). On partition, the monitor saves
the confirmed snapshot's host-readable leaves — replicated leaves carry the
FULL global value, so params/opt state survive intact — as a plain .npz
store with a JSON manifest. `restore_emergency` feeds it back through the
same tree-path-matching placement machinery as PR 4's topology-elastic
restore, so a survivor relaunched on the shrunk topology resumes with
bit-identical params.

Everything is opt-in via the `arch.fleet` config block; disabled (the
default) no thread starts, no KV key is written, and the host loops are
bit-identical (tests/test_fleet.py pins this).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from stoix_tpu.observability import flightrec, get_logger, get_registry
from stoix_tpu.resilience import faultinject
from stoix_tpu.resilience.errors import (
    FleetBarrierTimeout,
    FleetError,
    FleetPartitionError,
)

# Exit code of the partition path: distinct from Python's 1, the watchdog's
# 86 (EXIT_CODE_STALL), and SIGKILL's 137, so the launcher's supervision
# loop (stoix_tpu/launcher.py --supervise) can tell "peer died, relaunch at
# the surviving topology" apart from every other failure. Declared in the
# canonical registry (resilience/exit_codes.py, STX018); re-exported here
# because this module has owned the name since PR 7.
from stoix_tpu.resilience.exit_codes import EXIT_CODE_FLEET_PARTITION

# Per-host stop-flag bits, combined at window boundaries. Any nonzero flag
# anywhere in the fleet means EVERY host stops at that window.
FLAG_PREEMPT = 1  # SIGTERM/SIGINT observed on this host
FLAG_FAULT = 2  # host-local unrecoverable fault (embedder-raised)
FLAG_PARTITION = 4  # this host's monitor already declared a partition
# The integrity sentinel (resilience/integrity.py, docs/DESIGN.md §2.9)
# proved silent state corruption. Every host computes the same verdict from
# the same replicated fingerprint vector, so in the Anakin transport this
# flag is observability (all hosts already break at the same window); in the
# KV-vote transport it is the agreement carrier.
FLAG_CORRUPT = 8

MANIFEST_NAME = "fleet_manifest.json"
_STATE_FILE = "state.npz"
# numpy-native dtype kinds that np.savez round-trips faithfully; anything
# else (ml_dtypes bfloat16/float8 register as kind 'V') is cast to float32
# for storage and cast back to the template dtype on restore — lossless for
# the narrower float.
_PORTABLE_KINDS = frozenset("biufc")


class FleetStragglerWarning(UserWarning):
    """Typed slow-host warning: one host's window wall-time exceeded
    `skew_warn_ratio` x the fleet's fastest. A persistent straggler is the
    lockstep-all-reduce tax ROADMAP item 1's async learner groups
    (stoix_tpu/parallel/gossip.py, docs/DESIGN.md §2.12) exist to remove;
    this warning is how it becomes visible before it becomes a timeout."""


class FleetSettings(NamedTuple):
    """Resolved `arch.fleet` config block (defaults applied)."""

    enabled: bool
    heartbeat_interval_s: float
    heartbeat_timeout_s: float
    monitor_poll_s: float
    barrier_deadline_s: float
    skew_warn_ratio: float
    exit_grace_s: float
    emergency_dir: str


def settings_from_config(config: Any) -> FleetSettings:
    cfg = (config.get("arch") or {}).get("fleet") or {}
    return FleetSettings(
        enabled=bool(cfg.get("enabled", False)),
        heartbeat_interval_s=float(cfg.get("heartbeat_interval_s", 2.0)),
        heartbeat_timeout_s=float(cfg.get("heartbeat_timeout_s", 30.0)),
        monitor_poll_s=float(cfg.get("monitor_poll_s", 1.0)),
        barrier_deadline_s=float(cfg.get("barrier_deadline_s", 600.0)),
        skew_warn_ratio=float(cfg.get("skew_warn_ratio", 2.0)),
        exit_grace_s=float(cfg.get("exit_grace_s", 30.0)),
        emergency_dir=str(
            cfg.get("emergency_dir") or os.path.join("checkpoints", "fleet_emergency")
        ),
    )


# ---------------------------------------------------------------------------
# Backends: the jax.distributed KV store, and an in-process fake for tests.
# ---------------------------------------------------------------------------


class JaxKVBackend:
    """The live `jax.distributed` coordination-service KV store. All keys are
    namespaced under `stoix_tpu/fleet/` so they can never collide with jax's
    own coordination keys."""

    _PREFIX = "stoix_tpu/fleet/"

    def __init__(self, client: Any, process_index: int, process_count: int):
        self._client = client
        self.process_index = int(process_index)
        self.process_count = int(process_count)

    def _k(self, key: str) -> str:
        return self._PREFIX + key

    def put(self, key: str, value: str) -> None:
        # allow_overwrite: the coordination service's set is write-once by
        # default, and heartbeats REWRITE their key every interval — without
        # it every beat after the first fails and the whole fleet reads as
        # stale. Older clients without the kwarg get delete-then-set.
        try:
            self._client.key_value_set(self._k(key), str(value), allow_overwrite=True)
        except TypeError:
            try:
                self._client.key_value_delete(self._k(key))
            except Exception:  # noqa: STX003 — a missing key is the normal first-write case
                pass
            self._client.key_value_set(self._k(key), str(value))

    def try_get(self, key: str) -> Optional[str]:
        """Non-blocking-ish read: a missing key answers None within ~one
        coordination-RPC round-trip (this jax exposes no try_get, so a 50ms
        blocking get is the probe)."""
        try:
            return self._client.blocking_key_value_get(self._k(key), 50)
        except Exception:  # noqa: STX003 — NotFound/timeout both mean "no value yet"; the monitor treats None as a stale beat
            return None

    def get_blocking(self, key: str, timeout_s: float) -> Optional[str]:
        try:
            return self._client.blocking_key_value_get(
                self._k(key), max(1, int(timeout_s * 1000))
            )
        except Exception:  # noqa: STX003 — a deadline-exceeded RPC means the peer never wrote; the caller converts None into FleetPartitionError
            return None

    def barrier(self, name: str, timeout_s: float) -> bool:
        try:
            self._client.wait_at_barrier(self._k(name), max(1, int(timeout_s * 1000)))
            return True
        except Exception:  # noqa: STX003 — barrier timeout; the caller raises the typed FleetBarrierTimeout
            return False


class FakeFleetStore:
    """Shared in-process stand-in for the distributed KV store: N `view()`s
    of one store behave like N processes' backends. This is the test seam —
    agreement votes, heartbeat staleness, and monitor thresholds all run in
    tier-1 with zero subprocesses."""

    def __init__(self, num_processes: int):
        self.num_processes = int(num_processes)
        self._cond = threading.Condition()
        self._data: Dict[str, str] = {}
        self._barriers: Dict[str, set] = {}

    def view(self, process_index: int) -> "FakeFleetBackend":
        return FakeFleetBackend(self, process_index)

    # -- store side, called by views ----------------------------------------
    def put(self, key: str, value: str) -> None:
        with self._cond:
            self._data[key] = str(value)
            self._cond.notify_all()

    def try_get(self, key: str) -> Optional[str]:
        with self._cond:
            return self._data.get(key)

    def get_blocking(self, key: str, timeout_s: float) -> Optional[str]:
        with self._cond:
            self._cond.wait_for(lambda: key in self._data, timeout=timeout_s)
            return self._data.get(key)

    def barrier(self, name: str, timeout_s: float, process_index: int) -> bool:
        with self._cond:
            arrived = self._barriers.setdefault(name, set())
            arrived.add(int(process_index))
            self._cond.notify_all()
            return self._cond.wait_for(
                lambda: len(self._barriers.get(name, ())) >= self.num_processes,
                timeout=timeout_s,
            )


class FakeFleetBackend:
    """One process's view of a FakeFleetStore (same protocol as
    JaxKVBackend)."""

    def __init__(self, store: FakeFleetStore, process_index: int):
        self._store = store
        self.process_index = int(process_index)
        self.process_count = store.num_processes

    def put(self, key: str, value: str) -> None:
        self._store.put(key, value)

    def try_get(self, key: str) -> Optional[str]:
        return self._store.try_get(key)

    def get_blocking(self, key: str, timeout_s: float) -> Optional[str]:
        return self._store.get_blocking(key, timeout_s)

    def barrier(self, name: str, timeout_s: float) -> bool:
        return self._store.barrier(name, timeout_s, self.process_index)


def live_backend() -> Optional[JaxKVBackend]:
    """The real KV backend when `jax.distributed.initialize` has run in this
    process; None otherwise (single-process runs need no store)."""
    try:
        import jax
        from jax._src import distributed

        client = getattr(distributed.global_state, "client", None)
    except Exception:  # noqa: STX003 — a jax build without the distributed service simply has no fleet store
        return None
    if client is None:
        return None
    return JaxKVBackend(client, jax.process_index(), jax.process_count())


# ---------------------------------------------------------------------------
# Decisions
# ---------------------------------------------------------------------------


def describe_flags(bits: int) -> str:
    names = []
    if bits & FLAG_PREEMPT:
        names.append("preempt")
    if bits & FLAG_FAULT:
        names.append("fault")
    if bits & FLAG_PARTITION:
        names.append("partition")
    if bits & FLAG_CORRUPT:
        names.append("corrupt")
    return "+".join(names) if names else "healthy"


class FleetDecision(NamedTuple):
    """The combined window-boundary verdict: identical on every host because
    it is a pure function of the same exchanged flag set."""

    stop: bool
    flags: Dict[int, int]  # process_index -> flag bits

    @property
    def stopping_processes(self) -> List[int]:
        return sorted(p for p, f in self.flags.items() if f)

    def describe(self) -> str:
        if not self.stop:
            return "fleet healthy"
        parts = ", ".join(
            f"process {p}: {describe_flags(f)}" for p, f in sorted(self.flags.items()) if f
        )
        return f"fleet stop agreed ({parts})"


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------


class FleetCoordinator:
    """Owns this process's fleet membership: local stop flags, the heartbeat
    publisher + peer monitor threads, agreement transport, skew telemetry,
    and the local-shard emergency checkpoint. Construct via
    `fleet_from_config`; `start()` before the host loop, `stop()` in its
    finally."""

    def __init__(
        self,
        settings: FleetSettings,
        backend: Optional[Any] = None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        allgather_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        interrupt_on_partition: bool = True,
    ):
        self.settings = settings
        self._backend = backend
        if process_index is None or process_count is None:
            if backend is not None:
                process_index = backend.process_index
                process_count = backend.process_count
            else:
                import jax

                process_index = jax.process_index()
                process_count = jax.process_count()
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self._allgather_fn = allgather_fn
        self._interrupt_on_partition = bool(interrupt_on_partition)

        self._flag_lock = threading.Lock()
        self._local_flags = 0
        self._last_wall: Optional[float] = None
        self._stop_notes: List[str] = []

        self._stop_event = threading.Event()
        self._publisher: Optional[threading.Thread] = None
        self._monitor: Optional[threading.Thread] = None

        self.partition_event = threading.Event()
        self._partition_error: Optional[FleetPartitionError] = None
        self._exit_timer: Optional[threading.Timer] = None

        self._rescue_lock = threading.Lock()
        self._candidates: Dict[int, Any] = {}
        self._confirmed: Optional[Tuple[int, Any]] = None
        self._saved_path: Optional[str] = None

        self._prev_excepthook = None
        self._log = get_logger("stoix_tpu.resilience")

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetCoordinator":
        self._install_excepthook()
        if self._backend is not None and self.process_count > 1:
            self._backend.put(f"hb/{self.process_index}", "0")
            self._publisher = threading.Thread(
                target=self._publisher_loop, name="fleet-heartbeat", daemon=True
            )
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="fleet-monitor", daemon=True
            )
            self._publisher.start()
            self._monitor.start()
            self._log.info(
                "[fleet] coordination live: process %d/%d, heartbeat every "
                "%.1fs, peer deadline %.1fs",
                self.process_index, self.process_count,
                self.settings.heartbeat_interval_s,
                self.settings.heartbeat_timeout_s,
            )
        return self

    def stop(self) -> None:
        self._stop_event.set()
        for thread in (self._publisher, self._monitor):
            if thread is not None:
                thread.join(timeout=5.0)
        self._publisher = self._monitor = None
        # Always disarm the hard-exit timer: its one job is shooting a main
        # thread WEDGED inside a dead collective, and a main thread that
        # reached this stop() (the host loop's finally) has provably escaped.
        # From here the typed error propagates normally — callers may catch
        # it, and the uncaught case still exits 87 via the excepthook below.
        if self._exit_timer is not None:
            self._exit_timer.cancel()
        # Keep the excepthook installed across a partition: the
        # FleetPartitionError propagating out of the host loop AFTER this
        # stop() is exactly what the hook translates into the fleet exit
        # code for the supervising launcher.
        if not self.partition_event.is_set():
            self._restore_excepthook()

    def __enter__(self) -> "FleetCoordinator":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.stop()

    # -- local flags ----------------------------------------------------------
    def request_stop(self, flag: int, note: str = "") -> None:
        """Record a host-local stop reason (idempotent). The fleet acts on it
        at the NEXT window-boundary agreement, so all hosts act together."""
        with self._flag_lock:
            already = bool(self._local_flags & flag)
            self._local_flags |= int(flag)
            if note:
                self._stop_notes.append(note)
        if not already:
            get_registry().counter(
                "stoix_tpu_fleet_stop_requests_total",
                "Host-local fleet stop requests, by reason",
            ).inc(labels={"reason": describe_flags(flag)})
            self._log.warning(
                "[fleet] process %d requesting fleet stop (%s)%s — peers will "
                "agree at the next window boundary",
                self.process_index, describe_flags(flag),
                f": {note}" if note else "",
            )

    @property
    def local_flags(self) -> int:
        with self._flag_lock:
            return self._local_flags

    # -- agreement + telemetry: device piggyback (Anakin) ---------------------
    def _per_device_vector(self, mesh: Any, value: np.ndarray) -> Any:
        """A [num_devices] global array carrying `value` (a length-1 host
        array) on each of THIS host's mesh devices, assembled shard-wise.
        After the fetch's replicate collective materializes, every host holds
        every host's value at its devices' positions."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        devices = list(mesh.devices.flatten())
        sharding = NamedSharding(mesh, PartitionSpec(tuple(mesh.axis_names)))
        local = [
            jax.device_put(value, d)
            for d in devices
            if d.process_index == self.process_index
        ]
        return jax.make_array_from_single_device_arrays(
            (len(devices),), sharding, local
        )

    def note_window_wall(self, wall_s: float) -> None:
        """Record this host's most recent window wall-time; the NEXT
        `telemetry_for_fetch` ships it fleet-wide. Going through coordinator
        state (rather than a separate process_allgather at the window
        boundary) keeps the cross-host collective SEQUENCE identical to the
        fetch stream — a second, host-side collective interleaving with the
        still-executing async fetch collectives is exactly the mismatched-op
        crash Gloo punishes."""
        with self._flag_lock:
            self._last_wall = float(wall_s)

    def telemetry_for_fetch(self, mesh: Any) -> Dict[str, Any]:
        """The per-device fleet payload to merge into the coalesced metric
        fetch: the stop-flag byte (agreement) and the most recent window
        wall-time (straggler skew), both riding the all-reduce that was
        already being paid — zero extra collectives."""
        with self._flag_lock:
            last_wall = getattr(self, "_last_wall", None)
        flag = np.asarray([self.local_flags], dtype=np.uint8)
        wall = np.asarray(
            [np.nan if last_wall is None else last_wall], dtype=np.float32
        )
        if self.process_count == 1:
            return {"flags": flag, "wall": wall}
        return {
            "flags": self._per_device_vector(mesh, flag),
            "wall": self._per_device_vector(mesh, wall),
        }

    def _per_process(self, values: Any, mesh: Any = None) -> Dict[int, float]:
        """Fold a materialized per-device vector into {process: value}.
        Element order follows `mesh.devices.flatten()` (the sharding places
        shard i on flattened device i)."""
        flat = np.asarray(values).reshape(-1)
        if mesh is None or self.process_count == 1:
            return {self.process_index: flat.max(initial=0)}
        per_process: Dict[int, float] = {}
        for device, value in zip(mesh.devices.flatten(), flat):
            p = int(device.process_index)
            per_process[p] = max(per_process.get(p, value), value)
        return per_process

    def decide_from_fetch(self, payload: Any, mesh: Any = None) -> FleetDecision:
        """Combine a materialized `telemetry_for_fetch` payload (or a bare
        flag vector) into the fleet decision — a pure function of the shared
        replicated data, so every host computes the same verdict."""
        flags = payload["flags"] if isinstance(payload, dict) else payload
        values = np.asarray(flags).reshape(-1)
        if mesh is None or self.process_count == 1:
            per_process = {self.process_index: int(values.max(initial=0))}
        else:
            per_process: Dict[int, int] = {}
            for device, value in zip(mesh.devices.flatten(), values):
                p = int(device.process_index)
                per_process[p] = per_process.get(p, 0) | int(value)
        return FleetDecision(any(per_process.values()), per_process)

    def skew_from_fetch(
        self, payload: Any, mesh: Any, window_idx: int
    ) -> Optional[float]:
        """Export straggler-skew telemetry from a materialized fetch payload.
        Returns the slowest/fastest ratio, or None while any host has not yet
        reported a wall-time (the first windows ship NaN)."""
        if not isinstance(payload, dict) or "wall" not in payload:
            return None
        walls_by_process = self._per_process(payload["wall"], mesh)
        walls = {p: float(w) for p, w in walls_by_process.items()}
        if any(np.isnan(w) for w in walls.values()):
            return None
        return self._export_skew(walls, window_idx)

    # -- agreement: KV votes (Sebulba / host-path) ----------------------------
    def agree_at_window(
        self, window_idx: int, timeout_s: Optional[float] = None
    ) -> FleetDecision:
        """Window-indexed vote exchange through the KV store: every host
        publishes its flags under `vote/<window>/<pid>` then reads every
        peer's vote for the SAME window with a bounded blocking get. All
        hosts compute the decision from the same vote set, so all stop at
        the same window. A peer that never votes within the deadline is a
        partition."""
        flags = self.local_flags
        if self._backend is None or self.process_count == 1:
            return FleetDecision(flags != 0, {self.process_index: flags})
        deadline = (
            float(timeout_s) if timeout_s is not None
            else self.settings.barrier_deadline_s
        )
        self._backend.put(f"vote/{int(window_idx)}/{self.process_index}", str(flags))
        votes: Dict[int, int] = {}
        missing: List[int] = []
        for p in range(self.process_count):
            raw = self._backend.get_blocking(f"vote/{int(window_idx)}/{p}", deadline)
            if raw is None:
                missing.append(p)
            else:
                votes[p] = int(raw)
        if missing:
            raise self._declare_partition(
                missing, deadline, detail=f"no agreement vote for window {window_idx}"
            )
        return FleetDecision(any(votes.values()), votes)

    # -- heartbeats + partition detection -------------------------------------
    def _publisher_loop(self) -> None:
        seq = 0
        while not self._stop_event.wait(self.settings.heartbeat_interval_s):
            seq += 1
            try:
                self._backend.put(f"hb/{self.process_index}", str(seq))
            except Exception as exc:  # noqa: STX003 — a failed beat must not kill the publisher; peers will see us stale, which IS the signal
                self._log.warning("[fleet] heartbeat publish failed: %s", exc)

    def _monitor_loop(self) -> None:
        peers = [p for p in range(self.process_count) if p != self.process_index]
        last_value: Dict[int, Optional[str]] = {p: None for p in peers}
        started = time.monotonic()
        last_change: Dict[int, float] = {p: started for p in peers}
        age_gauge = get_registry().gauge(
            "stoix_tpu_fleet_heartbeat_age_seconds",
            "Seconds since each peer process's fleet heartbeat last advanced",
        )
        while not self._stop_event.wait(self.settings.monitor_poll_s):
            now = time.monotonic()
            stale: List[int] = []
            for p in peers:
                value = self._backend.try_get(f"hb/{p}")
                if value is not None and value != last_value[p]:
                    last_value[p] = value
                    last_change[p] = now
                age = now - last_change[p]
                age_gauge.set(age, {"process": str(p)})
                if age > self.settings.heartbeat_timeout_s:
                    stale.append(p)
            if stale:
                self._on_partition(stale)
                return

    def _declare_partition(
        self, missing: List[int], deadline_s: float, detail: str
    ) -> FleetPartitionError:
        """Record a partition verdict (idempotent) and return the typed
        error. Shared by the monitor thread and the vote path."""
        with self._flag_lock:
            self._local_flags |= FLAG_PARTITION
        if self._partition_error is None:
            self._partition_error = FleetPartitionError(missing, deadline_s, detail)
            get_registry().counter(
                "stoix_tpu_fleet_partitions_total",
                "Fleet partitions declared by this process",
            ).inc()
            self.partition_event.set()
            self._log.error(
                "[fleet] %s: %s",
                type(self._partition_error).__name__, self._partition_error,
            )
            flightrec.get_flight_recorder().record(
                "fleet_partition", missing=list(missing), deadline_s=float(deadline_s),
                detail=detail,
            )
        return self._partition_error

    def _on_partition(self, stale: List[int]) -> None:
        """Monitor-thread partition handler: declare, rescue-save, interrupt
        the (possibly natively-wedged) main thread, and arm the hard exit."""
        self._declare_partition(
            stale, self.settings.heartbeat_timeout_s, detail="heartbeat silent"
        )
        # The rescue save runs HERE, on the monitor thread: the main thread
        # may be blocked inside a collective that will never complete, and
        # the confirmed snapshot is readable without it (see emergency_save).
        try:
            self.emergency_save()
        except Exception as exc:  # noqa: STX003 — the exit path must proceed to the interrupt/hard-exit even if the rescue save fails
            self._log.error("[fleet] emergency save failed: %s", exc)
        if self._interrupt_on_partition:
            if self.settings.exit_grace_s > 0:
                self._exit_timer = threading.Timer(
                    self.settings.exit_grace_s, self._hard_exit
                )
                self._exit_timer.daemon = True
                self._exit_timer.start()
            import _thread

            _thread.interrupt_main()

    def _dump_flight_record(self, reason: str) -> None:
        """rc-87 flight record, next to the emergency rescue artifacts. Only
        the paths where the PROCESS actually dies with the fleet code dump
        (excepthook and hard exit) — a declared-but-handled partition in a
        unit test must not litter files (docs/DESIGN.md §2.13)."""
        flightrec.dump_flight_record(
            self.settings.emergency_dir,
            reason=reason,
            exit_code=EXIT_CODE_FLEET_PARTITION,
        )

    def _hard_exit(self) -> None:
        self._log.error(
            "[fleet] main thread still wedged %.0fs after the partition was "
            "declared (dead collective is uninterruptible) — hard exit %d",
            self.settings.exit_grace_s, EXIT_CODE_FLEET_PARTITION,
        )
        self._dump_flight_record(
            f"fleet partition hard exit: {self._partition_error}"
        )
        sys.stderr.flush()
        os._exit(EXIT_CODE_FLEET_PARTITION)

    def check_partition(self) -> None:
        """Raise the monitor's verdict on the calling thread, if one exists.
        Host loops call this at window/update boundaries so a partition
        detected while the main thread was in Python surfaces as the typed
        error instead of a bare KeyboardInterrupt."""
        if self.partition_event.is_set() and self._partition_error is not None:
            raise self._partition_error

    @property
    def partition_error(self) -> Optional[FleetPartitionError]:
        return self._partition_error

    # -- exit-code translation ------------------------------------------------
    def _install_excepthook(self) -> None:
        prev = sys.excepthook
        self._prev_excepthook = prev

        def hook(exc_type, exc, tb):
            prev(exc_type, exc, tb)
            if isinstance(exc, FleetError):
                self._dump_flight_record(f"fleet partition: {exc}")
                sys.stderr.flush()
                os._exit(EXIT_CODE_FLEET_PARTITION)

        self._hook = hook
        sys.excepthook = hook

    def _restore_excepthook(self) -> None:
        # Restore ONLY if the installed hook is still ours: another layer
        # (the integrity sentinel's 88-hook, §2.9) may have chained on top
        # of us after install — blindly re-assigning our saved prev would
        # silently uninstall IT.
        if self._prev_excepthook is not None and sys.excepthook is getattr(
            self, "_hook", None
        ):
            sys.excepthook = self._prev_excepthook
            self._prev_excepthook = None

    # -- straggler skew telemetry ---------------------------------------------
    def observe_window_wall(self, window_idx: int, wall_s: float) -> Optional[float]:
        """Exchange this window's host wall-time with every peer via
        `process_allgather` and export the skew telemetry. Returns the ratio
        (None single-process). This is the HOST-PATH transport (Sebulba's
        update loop, which runs no concurrent cross-host device collectives);
        the Anakin runner must use the fetch piggyback
        (`telemetry_for_fetch`/`skew_from_fetch`) instead — a host-side
        gather interleaving with its still-executing async fetch collectives
        would misorder the collective stream."""
        if self.process_count == 1:
            get_registry().gauge(
                "stoix_tpu_fleet_window_wall_seconds",
                "Per-host wall time of the most recent eval window",
            ).set(float(wall_s), {"process": str(self.process_index)})
            return None
        gather = self._allgather_fn
        if gather is None:
            from stoix_tpu.parallel import process_allgather

            gather = process_allgather
        walls = np.asarray(
            gather(np.asarray([float(wall_s)], dtype=np.float64))
        ).reshape(-1)
        return self._export_skew(
            {p: float(w) for p, w in enumerate(walls)}, window_idx
        )

    def _export_skew(
        self, walls: Dict[int, float], window_idx: int
    ) -> Optional[float]:
        """Export per-host wall gauges + the max/min skew ratio; a host
        slower than `skew_warn_ratio` x the fastest warns with the typed
        FleetStragglerWarning."""
        registry = get_registry()
        wall_gauge = registry.gauge(
            "stoix_tpu_fleet_window_wall_seconds",
            "Per-host wall time of the most recent eval window",
        )
        for p, wall in walls.items():
            wall_gauge.set(wall, {"process": str(p)})
        if len(walls) < 2:
            return None
        fastest = min(walls.values())
        slowest = max(walls.values())
        ratio = slowest / fastest if fastest > 0 else 1.0
        registry.gauge(
            "stoix_tpu_fleet_window_skew_ratio",
            "Slowest-host / fastest-host wall-time ratio for the most recent window",
        ).set(ratio)
        if ratio > self.settings.skew_warn_ratio:
            straggler = max(walls, key=lambda p: walls[p])
            registry.counter(
                "stoix_tpu_fleet_straggler_warnings_total",
                "Windows whose host wall-time skew exceeded skew_warn_ratio",
            ).inc(labels={"process": str(straggler)})
            message = (
                f"window {window_idx}: process {straggler} is a straggler — "
                f"{slowest:.2f}s vs fastest {fastest:.2f}s "
                f"({ratio:.1f}x > skew_warn_ratio {self.settings.skew_warn_ratio:.1f}); "
                f"the lockstep all-reduce runs at the slowest host's pace"
            )
            warnings.warn(FleetStragglerWarning(message), stacklevel=2)
            self._log.warning("[fleet] %s", message)
        return ratio

    # -- deadline-guarded barriers --------------------------------------------
    def barrier(self, name: str, deadline_s: Optional[float] = None) -> None:
        deadline = (
            float(deadline_s) if deadline_s is not None
            else self.settings.barrier_deadline_s
        )
        guarded_barrier(name, self._backend, deadline, exit_grace_s=self.settings.exit_grace_s)

    # -- local-shard emergency checkpoint -------------------------------------
    def stage_candidate(self, step: int, state: Any) -> None:
        """Stage an on-device snapshot COPY of the learner state for window
        `step`. The copy was enqueued on the device stream right after the
        window's learn program, so its completion is implied by the window's
        metrics materializing — at which point `confirm_candidate` promotes
        it to the rescue snapshot the partition path may save. A small dict
        (not a single slot): the pipelined runner stages window k+1's
        candidate BEFORE window k's confirmation arrives, so the in-flight
        and the just-staged candidate must coexist."""
        with self._rescue_lock:
            self._candidates[int(step)] = state
            while len(self._candidates) > 2:
                del self._candidates[min(self._candidates)]

    def confirm_candidate(self, step: int) -> None:
        with self._rescue_lock:
            state = self._candidates.get(int(step))
            if state is None:
                return
            self._confirmed = (int(step), state)
            # Confirmed supersedes everything at or below it.
            for stale in [s for s in self._candidates if s <= int(step)]:
                del self._candidates[stale]

    def emergency_save(self) -> Optional[str]:
        """Write the confirmed rescue snapshot's host-readable leaves to
        `<emergency_dir>/p<process_index>/` as state.npz + manifest
        (idempotent; returns the directory, or None with nothing staged).

        Replicated leaves carry the FULL global value (each host's
        addressable shard IS the array), so params and optimizer state
        survive a partition intact. Leaves that are only partially
        addressable from this host (data-sharded env state, per-shard RNG
        keys) are topology-bound anyway — they are recorded in the manifest
        and reinitialized from the template on restore, exactly like the
        topology-dependent leaves of PR 4's elastic restore."""
        with self._rescue_lock:
            if self._saved_path is not None:
                return self._saved_path
            staged = self._confirmed
        if staged is None:
            self._log.warning(
                "[fleet] no confirmed rescue snapshot to save (partition "
                "before the first completed window?)"
            )
            return None
        step, state = staged
        import jax

        from stoix_tpu.resilience import integrity
        from stoix_tpu.utils.checkpointing import _path_key

        directory = os.path.join(
            self.settings.emergency_dir, f"p{self.process_index}"
        )
        os.makedirs(directory, exist_ok=True)
        arrays: Dict[str, np.ndarray] = {}
        partial: List[str] = []
        casts: Dict[str, str] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
            key = "/".join(_path_key(path))
            value = self._host_value(leaf)
            if value is None:
                partial.append(key)
                continue
            arr = np.asarray(value)
            if arr.dtype.kind not in _PORTABLE_KINDS:
                casts[key] = str(arr.dtype)
                arr = arr.astype(np.float32)
            arrays[key] = arr
        # Per-leaf sha256 manifest (resilience/integrity.py — the shared
        # digest module also used by the orbax _digests.json sidecar and the
        # serving canary): restore verifies every leaf's bytes, so bit-rot
        # in a rescue store is rejected instead of resumed.
        digests = integrity.digest_arrays(arrays)
        np.savez(os.path.join(directory, _STATE_FILE), **arrays)
        manifest = {
            "format": 1,
            "step": int(step),
            "process_index": self.process_index,
            "process_count": self.process_count,
            "partial": sorted(partial),
            "casts": casts,
            "digests": digests,
        }
        tmp = os.path.join(directory, MANIFEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
        os.replace(tmp, os.path.join(directory, MANIFEST_NAME))
        with self._rescue_lock:
            self._saved_path = directory
        self._log.warning(
            "[fleet] local-shard emergency checkpoint secured: step %d, %d "
            "leaf(s) (%d topology-bound leaf(s) skipped) at %s — resume with "
            "logger.checkpointing.load_model=true "
            "logger.checkpointing.load_args.load_path=%s",
            step, len(arrays), len(partial), directory, self.settings.emergency_dir,
        )
        return directory

    @staticmethod
    def _host_value(leaf: Any) -> Optional[np.ndarray]:
        """The full host value of a leaf, or None when this host cannot see
        all of it (partially-addressable shard of a dead-peer global)."""
        import jax

        if not isinstance(leaf, jax.Array):
            return np.asarray(leaf)
        try:
            if leaf.sharding.is_fully_replicated:
                return np.asarray(leaf.addressable_data(0))
            if leaf.is_fully_addressable:
                return np.asarray(leaf)
        except Exception:  # noqa: STX003 — a deleted/donated buffer cannot be rescued; record it as partial rather than lose the save
            return None
        return None


def guarded_barrier(
    name: str,
    backend: Any,
    deadline_s: float,
    exit_grace_s: float = 0.0,
) -> None:
    """Cross-host barrier under a deadline watchdog (PR 4's stage machinery
    with a fleet error factory): a peer that never arrives raises
    FleetBarrierTimeout — with an all-thread stack dump — instead of hanging.
    The watchdog deadline trails the backend's own timeout slightly, so the
    backend's bounded wait answers first when it CAN; the watchdog is the
    backstop for a backend whose native wait outlives its nominal timeout."""
    from stoix_tpu.resilience.watchdog import Watchdog

    if backend is None:
        return
    with Watchdog(
        f"fleet_barrier:{name}",
        deadline_s + min(5.0, 0.25 * deadline_s + 0.5),
        hard_exit_grace_s=exit_grace_s,
        error_factory=lambda _stage, _deadline, dump: FleetBarrierTimeout(
            name, deadline_s, dump=dump
        ),
        exit_code=EXIT_CODE_FLEET_PARTITION,
    ):
        faultinject.maybe_barrier_wedge(name)
        if not backend.barrier(name, deadline_s):
            raise FleetBarrierTimeout(name, deadline_s)


# ---------------------------------------------------------------------------
# Emergency-store restore (feeds PR 4's tree-path placement machinery)
# ---------------------------------------------------------------------------


def _find_manifests(path: str) -> List[str]:
    direct = os.path.join(path, MANIFEST_NAME)
    if os.path.isfile(direct):
        return [direct]
    try:
        entries = os.listdir(path)
    except OSError:
        return []

    def _index(entry: str) -> Tuple[int, str]:
        # Numeric survivor order: 'p10' must sort AFTER 'p2', or the
        # documented lowest-process-index-wins tie-break silently picks the
        # wrong store on pods with >= 10 survivors.
        if entry.startswith("p") and entry[1:].isdigit():
            return (int(entry[1:]), entry)
        return (1 << 30, entry)

    found = []
    for entry in sorted(entries, key=_index):
        candidate = os.path.join(path, entry, MANIFEST_NAME)
        if os.path.isfile(candidate):
            found.append(candidate)
    return found


def is_emergency_store(path: Any) -> bool:
    """Whether `path` holds a fleet local-shard emergency checkpoint (its own
    manifest, or per-survivor `p<N>/` subdirectories)."""
    return bool(path) and bool(_find_manifests(str(path)))


def emergency_step(path: str) -> Optional[int]:
    """The step recorded in the winning survivor's manifest (None when `path`
    is not an emergency store) — a manifest-only read, cheap enough for the
    serving hot-swap watcher to poll."""
    manifests = _find_manifests(str(path))
    if not manifests:
        return None
    try:
        with open(manifests[0]) as f:
            return int(json.load(f)["step"])
    except (OSError, ValueError, KeyError):
        return None


def read_emergency_raw(path: str) -> Tuple[Dict[str, np.ndarray], Dict[str, str], int]:
    """Read a fleet emergency store's host leaves WITHOUT a template:
    (arrays keyed by slash-joined tree path, the manifest's storage-widening
    cast record, the saved step). With several survivors' stores present,
    the lowest process index wins — replicated leaves are identical across
    survivors by construction. Shared by restore_emergency (below) and the
    serving loader (stoix_tpu/serve/checkpoint.py), which restores only the
    actor-params subtree."""
    manifests = _find_manifests(str(path))
    if not manifests:
        raise FileNotFoundError(f"no fleet emergency manifest under {path}")
    manifest_path = manifests[0]
    with open(manifest_path) as f:
        manifest = json.load(f)
    step = int(manifest["step"])
    directory = os.path.dirname(manifest_path)
    with np.load(os.path.join(directory, _STATE_FILE)) as data:
        raw = {key: data[key] for key in data.files}
    # Digest-verify every loaded leaf against the manifest (docs/DESIGN.md
    # §2.9): a rescue store that rotted on disk — or was truncated by the
    # dying host — must be rejected here, not resumed into a fleet that just
    # proved it cares about bit-level integrity.
    from stoix_tpu.resilience import integrity
    from stoix_tpu.resilience.errors import CheckpointIntegrityError

    mismatched = integrity.verify_digests(raw, dict(manifest.get("digests") or {}))
    if mismatched:
        raise CheckpointIntegrityError(
            step,
            f"emergency store {directory} failed sha256 verification for "
            f"{len(mismatched)} leaf(s): {', '.join(mismatched[:5])}"
            f"{'...' if len(mismatched) > 5 else ''}",
            kind="digest",
        )
    return raw, dict(manifest.get("casts") or {}), step


RESTORE_REPORT_NAME = "restore_report.json"


def read_restore_report(path: str) -> Optional[Dict[str, Any]]:
    """The report the most recent `restore_emergency` over `path` left behind
    (None when no restore has run, or the report is unreadable)."""
    try:
        with open(os.path.join(str(path), RESTORE_REPORT_NAME)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def restore_emergency(
    template: Any,
    path: str,
    raw_transform: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None,
) -> Tuple[Any, int]:
    """Restore a local-shard emergency store into `template`'s shardings via
    the same tree-path matching + placement as topology-elastic restore
    (utils/checkpointing.place_host_leaves): matched leaves round-trip
    through the host bit-identical; manifest-recorded partial leaves (and
    shape-mismatched topology-bound leaves) keep the template's fresh value.

    `raw_transform` is the elastic seam (docs/DESIGN.md §2.14): it rewrites
    the digest-verified host arrays BEFORE placement — the population
    shrink/grow transform re-places PBT member axes across a different P
    here, where the values are still plain host numpy. The restore leaves a
    `restore_report.json` next to the store recording the step, the sha256
    of every leaf actually placed (post-transform, so an elastic-off restore
    reports exactly the manifest digests), what was reinitialized, and the
    restore's own wall clock — the artifact the resize soak asserts
    digest-identity and recovery wall against from OUTSIDE the process."""
    import jax

    from stoix_tpu.resilience import integrity
    from stoix_tpu.utils.checkpointing import place_host_leaves

    t_start = time.perf_counter()
    raw, casts, step = read_emergency_raw(path)
    if raw_transform is not None:
        raw = dict(raw_transform(dict(raw)))
    # Digests of what is actually being placed, BEFORE the storage-width
    # cast-back (so with no transform they equal the manifest's digests,
    # which were computed over the stored widened arrays).
    placed_digests = integrity.digest_arrays(raw)
    # Cast storage-widened leaves back to the template's dtype (bfloat16 was
    # stored as float32 — lossless to round-trip through the wider float).
    template_dtypes = {
        "/".join(_leaf_path_key(p)): getattr(leaf, "dtype", np.asarray(leaf).dtype)
        for p, leaf in jax.tree_util.tree_flatten_with_path(template)[0]
    }
    for key in casts:
        if key in raw and key in template_dtypes:
            raw[key] = raw[key].astype(template_dtypes[key])
    raw_by_path = {tuple(key.split("/")): value for key, value in raw.items()}
    restored, matched, reinitialized, _reinit_keys = place_host_leaves(
        raw_by_path, template, step, allow_missing=True
    )
    get_logger("stoix_tpu.checkpoint").warning(
        "[fleet] emergency restore of step %d from %s: %d leaf(s) restored "
        "bit-identical, %d kept template initialization%s",
        step, path, matched, len(reinitialized),
        f" ({'; '.join(reinitialized)})" if reinitialized else "",
    )
    report = {
        "format": 1,
        "step": int(step),
        "source": str(path),
        "transformed": raw_transform is not None,
        "matched": int(matched),
        "reinitialized": list(reinitialized),
        "digests": placed_digests,
        "recovery_wall_s": time.perf_counter() - t_start,
        "unix_time": time.time(),
    }
    try:
        tmp = os.path.join(str(path), RESTORE_REPORT_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, os.path.join(str(path), RESTORE_REPORT_NAME))
    except OSError:
        # The report is a soak/bench observability artifact; a read-only
        # store must not fail the restore that just succeeded.
        get_logger("stoix_tpu.checkpoint").warning(
            "[fleet] could not write %s next to %s", RESTORE_REPORT_NAME, path
        )
    return restored, step


def _leaf_path_key(path: Any) -> Tuple[str, ...]:
    from stoix_tpu.utils.checkpointing import _path_key

    return _path_key(path)


def fleet_from_config(
    config: Any, backend: Optional[Any] = None
) -> Optional[FleetCoordinator]:
    """A started-able FleetCoordinator when `arch.fleet.enabled`, else None.
    `backend` injects a FakeFleetBackend for tests; by default the live
    jax.distributed KV store is used when one exists (single-process runs
    coordinate trivially with no store)."""
    settings = settings_from_config(config)
    if not settings.enabled:
        return None
    return FleetCoordinator(settings, backend=backend or live_backend())
