"""The canonical process exit-code registry (docs/DESIGN.md §2.6).

Every deliberate non-zero exit in `stoix_tpu/` — a watchdog shooting a
wedged backend, the fleet's partition path, the integrity sentinel's
corruption verdict, a CLI usage error — resolves to ONE constant declared
here. Before this module the codes were scattered per subsystem
(watchdog.py owned 86, fleet.py owned 87, integrity.py owned 88, the CLIs
used bare 2s), which worked exactly until the next subsystem picked a
number somebody else already meant something by: the supervising launcher
keys its relaunch policy on these integers, so a collision silently turns
"retry at the surviving topology" into "drain the allocation" (or worse,
the reverse).

STX018 (stoix_tpu/analysis/rules/stx018_exit_codes.py) enforces the
discipline from here on: an `os._exit(<int literal>)`/`sys.exit(<int
literal>)` anywhere in `stoix_tpu/`, or an `EXIT_CODE_*` name that does not
import from this module, is a lint error. The DESIGN.md §2.6 table is
cross-checked against `REGISTRY` by tests/test_threadmodel.py, so docs and
code cannot drift.

This module is dependency-free on purpose (stdlib only, no jax, no sibling
imports): it must be importable from a SLURM epilog, a CI triage script, or
the analysis gate without touching an accelerator.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

# Success / generic-failure codes. Declared for completeness (STX018 forces
# every literal through here); 0 and 1 keep their POSIX meanings.
EXIT_CODE_OK = 0
# Generic unrecoverable failure: an uncaught exception, or faultinject's
# host_loss path finishing the job after a SIGCONT. Final — never relaunch.
EXIT_CODE_FAILURE = 1
# CLI usage error (argparse's own convention): bad flags, unknown rule ids,
# mutually-exclusive options. Final.
EXIT_CODE_USAGE = 2
# The launch-hardening watchdog (resilience/watchdog.py, §2.4) shot a main
# thread wedged in native code past its stage deadline. Distinct from 1 and
# from SIGKILL's 137 so schedulers can tell "wedged, retry is reasonable"
# apart from a real crash.
EXIT_CODE_STALL = 86
# A fleet peer died and this host secured its local-shard emergency
# checkpoint (resilience/fleet.py, §2.6). `--supervise N` relaunches at the
# surviving topology with the emergency restore overrides.
EXIT_CODE_FLEET_PARTITION = 87
# The integrity sentinel proved silent state corruption and recorded the
# offender in the quarantine file (resilience/integrity.py, §2.9).
# `--supervise N` relaunches with the quarantine record's resume overrides.
EXIT_CODE_STATE_CORRUPTION = 88
# A deliberate topology resize (resilience/elastic.py, §2.14): the run
# secured an emergency snapshot and wrote a `resize_request.json` naming the
# target device count. Distinct from 87 so supervisor logs and flight
# records can tell "we chose to resize" from "a peer died under us".
# `--supervise N --elastic` relaunches at the requested topology with the
# emergency restore overrides; without `--elastic` it is final.
EXIT_CODE_ELASTIC_RESIZE = 89


class ExitCode(NamedTuple):
    code: int
    name: str
    meaning: str
    supervision: str  # what a supervising launcher should do with it


# The declaration tuple; uniqueness is validated over THIS (a dict
# comprehension would silently dedup by code — exactly the collision the
# registry exists to prevent) before REGISTRY is built from it.
_RECORDS: "tuple[ExitCode, ...]" = (
    ExitCode(
        EXIT_CODE_OK,
        "EXIT_CODE_OK",
        "clean finish, or coordinated graceful preemption",
        "none (resume via the regular checkpoint if preempted)",
    ),
    ExitCode(
        EXIT_CODE_FAILURE,
        "EXIT_CODE_FAILURE",
        "crash (traceback), or a `host_loss` victim finishing the job",
        "none — a bug, not a fleet event",
    ),
    ExitCode(
        EXIT_CODE_USAGE,
        "EXIT_CODE_USAGE",
        "CLI usage error (bad flags, unknown rule ids, conflicting modes)",
        "none — fix the invocation",
    ),
    ExitCode(
        EXIT_CODE_STALL,
        "EXIT_CODE_STALL",
        "watchdog shot a wedged backend (§2.4)",
        "retry is reasonable; not a fleet event",
    ),
    ExitCode(
        EXIT_CODE_FLEET_PARTITION,
        "EXIT_CODE_FLEET_PARTITION",
        "peer died, local-shard emergency checkpoint secured",
        "`--supervise N`: relaunch at the surviving topology with "
        "`load_model=true load_args.load_path=<emergency_dir>`",
    ),
    ExitCode(
        EXIT_CODE_STATE_CORRUPTION,
        "EXIT_CODE_STATE_CORRUPTION",
        "the integrity sentinel proved silent state corruption; offender "
        "recorded in the quarantine file (§2.9)",
        "`--supervise N`: relaunch with the quarantine record's resume "
        "overrides, restoring the newest digest-verified checkpoint",
    ),
    ExitCode(
        EXIT_CODE_ELASTIC_RESIZE,
        "EXIT_CODE_ELASTIC_RESIZE",
        "deliberate topology resize: emergency snapshot secured and "
        "`resize_request.json` names the target device count (§2.14)",
        "`--supervise N --elastic`: relaunch at the requested topology with "
        "the emergency restore overrides; without `--elastic` it is final",
    ),
)

# Uniqueness is the registry's entire point: a collision would mean two
# subsystems claiming one integer (or one name claiming two), which is
# exactly the bug class STX018 exists to prevent. Checked over the RECORD
# TUPLE at import — validating after a dict build would let the dict dedup
# a colliding code silently — so a bad edit fails the first test that
# touches resilience, not the first production triage.
_codes = [record.code for record in _RECORDS]
_names = [record.name for record in _RECORDS]
if len(set(_codes)) != len(_codes):  # pragma: no cover - guarded by tests
    raise RuntimeError(f"duplicate exit codes in registry: {sorted(_codes)}")
if len(set(_names)) != len(_names):  # pragma: no cover - guarded by tests
    raise RuntimeError(f"duplicate exit-code names in registry: {_names}")

# code -> full record; the §2.6 table renders from this (and the docs test
# cross-checks the rendered table against it).
REGISTRY: Dict[int, ExitCode] = {record.code: record for record in _RECORDS}


def design_table_rows() -> "list[str]":
    """The docs/DESIGN.md §2.6 exit-code table body, one markdown row per
    registered code. The table in the docs is pasted from here and
    tests/test_threadmodel.py cross-checks every row, so the docs and the
    registry cannot drift."""
    return [
        f"| {r.code} | `{r.name}`: {r.meaning} | {r.supervision} |"
        for r in sorted(REGISTRY.values())
    ]
