"""TelemetrySink: fans metrics-registry snapshots into the multi-sink logger.

Duck-typed against `stoix_tpu.utils.logger.BaseSink` (same `write`/`close`
signature) rather than subclassing it, so the observability package stays a
leaf with no imports from the rest of stoix_tpu.

Each logger write refreshes two files under `<exp_dir>/telemetry/`:

    metrics.prom   — Prometheus text exposition, atomically replaced
    metrics.jsonl  — one flattened snapshot row per write (offline forensics)

and `close()` writes a final snapshot plus the Chrome-trace/Perfetto span
export (`trace.json`), then shuts tracing down so a telemetry-enabled run
leaves no enabled global state behind for the next run in the process.
"""

from __future__ import annotations

import time
from os.path import join
from typing import Any, Dict, Optional

from stoix_tpu.observability.exporters import JsonlMetricsWriter, write_prometheus
from stoix_tpu.observability.registry import MetricsRegistry, get_registry
from stoix_tpu.observability.trace_export import write_chrome_trace


class TelemetrySink:
    def __init__(
        self,
        out_dir: str,
        registry: Optional[MetricsRegistry] = None,
        export_trace: bool = True,
        min_write_interval_s: float = 0.0,
    ):
        self.out_dir = out_dir
        self.prometheus_path = join(out_dir, "metrics.prom")
        self.trace_path = join(out_dir, "trace.json")
        self._registry = registry or get_registry()
        self._jsonl = JsonlMetricsWriter(join(out_dir, "metrics.jsonl"))
        self._export_trace = export_trace
        self._min_interval = float(min_write_interval_s)
        self._last_write = 0.0
        self._last_t = 0

    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: Any) -> None:
        self._last_t = int(t)
        now = time.monotonic()
        if self._min_interval and now - self._last_write < self._min_interval:
            return
        self._last_write = now
        write_prometheus(self.prometheus_path, self._registry)
        self._jsonl.write_snapshot(t, self._registry)

    def close(self) -> None:
        write_prometheus(self.prometheus_path, self._registry)
        self._jsonl.write_snapshot(self._last_t, self._registry)
        self._jsonl.close()
        if self._export_trace:
            write_chrome_trace(self.trace_path)
        from stoix_tpu import observability

        observability.shutdown()
