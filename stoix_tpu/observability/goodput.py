"""Goodput/badput ledger: wall-clock attribution over a fixed taxonomy
(docs/DESIGN.md §2.13).

Every second of a run is classified into exactly one of nine phases —

    compute     device learn steps making training progress (goodput)
    eval        evaluator dispatch/execution
    checkpoint  orbax serialization handed off on the host path
    fetch_wait  host blocked materializing the coalesced metric fetch
    queue_wait  Sebulba learner blocked collecting actor rollouts
    gossip      cross-group parameter mixing dispatch
    compile     AOT warmup / XLA compile
    stall       injected or detected host stalls (faultinject, watchdog)
    recovery    checkpoint restore, actor respawn backoff, rescue saves

— by consuming the phase timings the pipelined runner, the Sebulba core and
the serve worker already record. The ledger is pure host arithmetic over a
monotonic clock: no threads, no device work, always safe to run (the
`logger.telemetry.http` bit-identity pin holds with it active).

The attribution invariant: `finalize()` assigns the residual wall time (wall
minus the explicitly timed phases) to `compute`. In the pipelined Anakin
loop that residual IS device compute — the host dispatches in microseconds
and idles while the accelerator executes the window — so goodput is measured
as "wall time not proven to be anything else", the same convention Google's
goodput ladder uses. The fractions therefore sum to 1 exactly (±float
epsilon), which tests/test_opsplane.py pins on a real pipelined ff_ppo run.

Exported as `stoix_tpu_goodput_seconds_total{phase=...}` counters plus the
derived `stoix_tpu_goodput_fraction` gauge; bench payloads carry
`goodput {fraction, stall_s, recovery_s, fractions}` first-class.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Mapping, Optional

from stoix_tpu.observability.registry import MetricsRegistry, get_registry

# The fixed taxonomy. Order is presentation order in /statusz and DESIGN.md.
PHASES = (
    "compute",
    "eval",
    "checkpoint",
    "fetch_wait",
    "queue_wait",
    "gossip",
    "compile",
    "stall",
    "recovery",
)

# Anakin runner phase-clock names (stoix_tpu_runner_phase_seconds_total
# labels) -> taxonomy. learn_s is dispatch cost in the pipelined loop; the
# device execution it overlaps lands in the compute residual either way.
RUNNER_PHASE_MAP = {
    "compile_s": "compile",
    "learn_s": "compute",
    "gossip_s": "gossip",
    "eval_s": "eval",
    "fetch_s": "fetch_wait",
    "ckpt_s": "checkpoint",
}

# Sebulba TimingTracker keys -> taxonomy (learner-loop attribution).
# `ingest` is the off-policy poll/warmup-block path (ff_dqn): time spent
# waiting on actor experience, same class as the on-policy rollout collect.
SEBULBA_PHASE_MAP = {
    "rollout_get": "queue_wait",
    "ingest": "queue_wait",
    "assemble": "compute",
    "learn": "compute",
}


class GoodputLedger:
    """One run's attribution ledger. `start()` opens the wall clock;
    `note()`/`note_phases()` attribute explicitly timed seconds;
    `finalize()` closes the books, assigns the residual, exports the
    counters/gauge, and returns the report dict bench.py forwards."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry or get_registry()
        self._counter = self._registry.counter(
            "stoix_tpu_goodput_seconds_total",
            "Run wall-clock seconds attributed per goodput-taxonomy phase",
        )
        self._gauge = self._registry.gauge(
            "stoix_tpu_goodput_fraction",
            "Goodput (compute) fraction of wall time for the most recent run",
        )
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {phase: 0.0 for phase in PHASES}
        self._t0: Optional[float] = None

    def start(self) -> "GoodputLedger":
        self._t0 = time.perf_counter()
        return self

    def note(self, phase: str, seconds: float) -> None:
        if phase not in self._seconds:
            raise ValueError(
                f"unknown goodput phase {phase!r} (taxonomy: {PHASES})"
            )
        seconds = max(0.0, float(seconds))
        if seconds == 0.0:
            return
        with self._lock:
            self._seconds[phase] += seconds
        self._counter.inc(seconds, {"phase": phase})

    def note_phases(
        self, breakdown: Mapping[str, float], mapping: Optional[Mapping[str, str]] = None
    ) -> None:
        """Attribute a whole phase-breakdown dict at once. `mapping` renames
        source keys into the taxonomy (default: the Anakin runner names);
        keys already in the taxonomy pass through, unknown keys are refused
        loudly — an unmapped phase would silently inflate the residual."""
        mapping = dict(RUNNER_PHASE_MAP if mapping is None else mapping)
        for name, seconds in breakdown.items():
            phase = mapping.get(name, name)
            self.note(phase, seconds)

    def seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._seconds)

    def finalize(self, wall_s: Optional[float] = None) -> Dict[str, object]:
        """Close the ledger: residual wall time -> compute, fractions
        normalized to the (possibly clamped) wall so they always sum to 1."""
        if self._t0 is None and wall_s is None:
            raise RuntimeError("GoodputLedger.finalize() before start()")
        wall = float(wall_s) if wall_s is not None else time.perf_counter() - self._t0
        attributed = sum(self.seconds().values())
        residual = wall - attributed
        if residual > 0:
            self.note("compute", residual)
        else:
            # Explicitly timed phases can (rarely) over-cover the wall when
            # timers overlap; the books still balance by taking the
            # attributed total as the denominator.
            wall = attributed
        seconds = self.seconds()
        denom = wall if wall > 0 else 1.0
        fractions = {phase: seconds[phase] / denom for phase in PHASES}
        fraction = fractions["compute"]
        self._gauge.set(fraction)
        return {
            "wall_s": round(wall, 6),
            "fraction": round(fraction, 6),
            "stall_s": round(seconds["stall"], 6),
            "recovery_s": round(seconds["recovery"], 6),
            "seconds": {phase: round(seconds[phase], 6) for phase in PHASES},
            "fractions": {phase: fractions[phase] for phase in PHASES},
        }


_lock = threading.Lock()
_active: Optional[GoodputLedger] = None


def set_active(ledger: Optional[GoodputLedger]) -> None:
    """Install/clear the run's ledger so out-of-loop attribution sites
    (faultinject stalls, supervisor respawn backoff, watchdog verdicts) can
    feed it without threading a handle through every call chain."""
    global _active
    with _lock:
        _active = ledger


def get_active() -> Optional[GoodputLedger]:
    with _lock:
        return _active


def note_stall(seconds: float) -> None:
    """Attribute stall seconds to the active run's ledger (no-op between
    runs — a stall with no ledger has no wall clock to charge)."""
    ledger = get_active()
    if ledger is not None:
        ledger.note("stall", seconds)


def note_recovery(seconds: float) -> None:
    ledger = get_active()
    if ledger is not None:
        ledger.note("recovery", seconds)


def disabled_report() -> Dict[str, object]:
    """The schema-complete zero report for paths that never ran a ledger
    (bench fallback payloads): same keys, all-zero, fraction 0."""
    return {
        "wall_s": 0.0,
        "fraction": 0.0,
        "stall_s": 0.0,
        "recovery_s": 0.0,
        "seconds": {phase: 0.0 for phase in PHASES},
        "fractions": {phase: 0.0 for phase in PHASES},
    }
