"""Registry exporters: Prometheus text exposition + JSONL snapshots.

Both render `MetricsRegistry.snapshot()` output — point-in-time copies, so an
export never holds instrument locks while doing file I/O.

Prometheus text format (version 0.0.4): `# HELP` / `# TYPE` comment lines,
then one `name{label="value",...} value` sample per series. Histograms emit
the standard `_bucket{le=...}` cumulative series plus `_sum`/`_count`. The
file is written atomically (tmp + rename) so a scraper or test never reads a
half-written snapshot.

The JSONL sink appends one row per snapshot — `{"t": step, "time": unix,
"metrics": {flat_name: value}}` — flattening labeled series into
`name{k=v,...}` keys, for offline steps-per-second forensics without a
Prometheus server.
"""

from __future__ import annotations

import json
import math
import os
import re
import time
from typing import Any, Dict, Optional

from stoix_tpu.observability.registry import MetricsRegistry, get_registry

# Prometheus exposition-format identifier grammar (text format 0.0.4):
# metric names additionally allow ':' (recording-rule convention).
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_METRIC_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v != v:  # NaN
        return "NaN"
    return repr(float(v))


def sanitize_metric_name(name: str) -> str:
    """Spec-valid metric name: invalid characters collapse to '_' (and a
    leading digit gets a '_' prefix) rather than raising — an exporter must
    render whatever the process registered, not crash the scrape."""
    name = str(name)
    if _METRIC_NAME_RE.match(name):
        return name
    name = _METRIC_BAD_CHARS.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def sanitize_label_name(name: str) -> str:
    name = str(name)
    if _LABEL_NAME_RE.match(name):
        return name
    name = _LABEL_BAD_CHARS.sub("_", name)
    if not name or name[0].isdigit():
        name = "_" + name
    return name


def _escape_label_value(value: str) -> str:
    # Escaping order matters: backslash first, then quote and newline —
    # the three characters the spec requires escaped in label values.
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    # HELP text escapes backslash and newline only (quotes are legal there).
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        '%s="%s"' % (sanitize_label_name(k), _escape_label_value(v))
        for k, v in sorted(merged.items())
    )
    return "{%s}" % inner


def to_prometheus_text(registry: Optional[MetricsRegistry] = None) -> str:
    registry = registry or get_registry()
    lines = []
    for raw_name, family in sorted(registry.snapshot().items()):
        name = sanitize_metric_name(raw_name)
        # HELP then TYPE, emitted exactly once per family — every labeled
        # child series of the family renders below the single header pair.
        if family["help"]:
            lines.append(f"# HELP {name} {_escape_help(family['help'])}")
        lines.append(f"# TYPE {name} {family['kind']}")
        for series in family["series"]:
            labels = series["labels"]
            if family["kind"] == "histogram":
                for bound, count in sorted(series["buckets"].items()):
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, {'le': _fmt_value(bound)})} {count}"
                    )
                lines.append(f"{name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(series['summary']['sum'])}")
                lines.append(f"{name}_count{_fmt_labels(labels)} "
                             f"{series['summary']['count']}")
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels)} {_fmt_value(series['value'])}"
                )
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, registry: Optional[MetricsRegistry] = None) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(to_prometheus_text(registry))
    os.replace(tmp, path)
    return path


def flatten_snapshot(snapshot: Dict[str, Any]) -> Dict[str, float]:
    """{name{k=v,...}: value} — histograms contribute _count/_sum/_mean/_max."""
    flat: Dict[str, float] = {}
    for name, family in snapshot.items():
        for series in family["series"]:
            labels = series["labels"]
            suffix = (
                "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"
                if labels
                else ""
            )
            if family["kind"] == "histogram":
                summary = series["summary"]
                flat[f"{name}_count{suffix}"] = float(summary["count"])
                flat[f"{name}_sum{suffix}"] = float(summary["sum"])
                if summary["count"]:
                    flat[f"{name}_mean{suffix}"] = float(summary["mean"])
                    flat[f"{name}_max{suffix}"] = float(summary["max"])
            else:
                flat[f"{name}{suffix}"] = float(series["value"])
    return flat


class JsonlMetricsWriter:
    """Append-mode JSONL snapshot log (one row per call, flushed so a killed
    run keeps everything written so far)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._file = open(path, "a")
        self.path = path

    def write_snapshot(
        self, t: int, registry: Optional[MetricsRegistry] = None
    ) -> None:
        registry = registry or get_registry()
        row = {
            "t": int(t),
            "time": time.time(),
            "metrics": flatten_snapshot(registry.snapshot()),
        }
        self._file.write(json.dumps(row) + "\n")
        self._file.flush()

    def close(self) -> None:
        self._file.close()
