"""Process-wide metrics registry: counters, gauges, histograms with labels.

Zero-dependency (stdlib only). All instruments are host-side and thread-safe;
recording never touches a device or forces a host sync, so always-on recording
preserves the pipelined-loop guarantees (docs/DESIGN.md §2.1). Naming follows
the `stoix_tpu_<area>_<name>` convention (docs/DESIGN.md §2.2); labels are
plain string dicts and each distinct label set is its own series.

Snapshots (`MetricsRegistry.snapshot()`) are point-in-time copies consumed by
the exporters (observability/exporters.py: Prometheus text exposition + JSONL)
and by `RunStats` — the dict-compatible view that replaced the ad-hoc
module-level `LAST_RUN_STATS = {}` accumulators (lint rule STX002 forbids
those in library code).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# Bucket upper bounds (seconds) tuned for host-loop phases: sub-ms dispatch
# costs up to minutes-long stalls. +Inf is implicit.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 180.0,
)


def _label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    """One named metric family; per-label-set series live in `_series`."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str = ""):
        self.name = name
        self.help = help_text
        self._lock = threading.Lock()
        self._series: Dict[LabelKey, Any] = {}

    def labels_and_values(self) -> List[Tuple[LabelKey, Any]]:
        with self._lock:
            return list(self._series.items())


class Counter(_Instrument):
    """Monotonically increasing float per label set."""

    kind = "counter"

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease (got {amount})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class Gauge(_Instrument):
    """Last-write-wins float per label set."""

    kind = "gauge"

    def set(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, labels: Optional[Dict[str, str]] = None) -> None:
        self.inc(-amount, labels)

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))


class _HistogramSeries:
    __slots__ = ("count", "total", "minimum", "maximum", "bucket_counts")

    def __init__(self, n_buckets: int):
        self.count = 0
        self.total = 0.0
        self.minimum = float("inf")
        self.maximum = float("-inf")
        self.bucket_counts = [0] * (n_buckets + 1)  # last slot = +Inf


class Histogram(_Instrument):
    """Prometheus-style cumulative-bucket histogram per label set."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_text: str = "",
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_text)
        self.buckets: Tuple[float, ...] = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name} needs at least one bucket bound")

    def observe(self, value: float, labels: Optional[Dict[str, str]] = None) -> None:
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistogramSeries(len(self.buckets))
            series.count += 1
            series.total += value
            series.minimum = min(series.minimum, value)
            series.maximum = max(series.maximum, value)
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    series.bucket_counts[i] += 1
                    break
            else:
                series.bucket_counts[-1] += 1

    def summary(self, labels: Optional[Dict[str, str]] = None) -> Dict[str, float]:
        with self._lock:
            series = self._series.get(_label_key(labels))
            if series is None or series.count == 0:
                return {"count": 0, "sum": 0.0}
            return self._summarize(series)

    @staticmethod
    def _summarize(series: _HistogramSeries) -> Dict[str, float]:
        return {
            "count": series.count,
            "sum": series.total,
            "min": series.minimum,
            "max": series.maximum,
            "mean": series.total / series.count,
        }

    def export(self) -> List[Tuple[LabelKey, Dict[str, float], Dict[float, int]]]:
        """Atomic (summary, cumulative-buckets) pairs per label set — ONE
        critical section, so an exported snapshot keeps the Prometheus
        invariant count == +Inf bucket even while other threads observe."""
        out = []
        with self._lock:
            for key, series in self._series.items():
                cumulative, buckets = 0, {}
                for bound, n in zip(self.buckets, series.bucket_counts):
                    cumulative += n
                    buckets[bound] = cumulative
                buckets[float("inf")] = cumulative + series.bucket_counts[-1]
                out.append((key, self._summarize(series), buckets))
        return out


class MetricsRegistry:
    """Named instruments; get-or-create semantics so call sites never race on
    registration. One process-wide default lives behind `get_registry()`."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name: str, help_text: str, **kwargs) -> Any:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, help_text, **kwargs)
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
            return inst

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help_text)

    def histogram(
        self,
        name: str,
        help_text: str = "",
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, buckets=buckets)

    def instruments(self) -> List[_Instrument]:
        with self._lock:
            return list(self._instruments.values())

    def series_count(self) -> int:
        return sum(len(inst.labels_and_values()) for inst in self.instruments())

    def snapshot(self) -> Dict[str, Any]:
        """Point-in-time copy: {name: {"kind", "help", "series": [{"labels",
        "value"|"summary"}]}}. Histogram series carry count/sum/min/max/mean
        plus per-bucket cumulative counts keyed by upper bound."""
        out: Dict[str, Any] = {}
        for inst in self.instruments():
            series_list: List[Dict[str, Any]] = []
            if isinstance(inst, Histogram):
                for key, summary, buckets in inst.export():
                    series_list.append(
                        {"labels": dict(key), "summary": summary, "buckets": buckets}
                    )
            else:
                for key, raw in inst.labels_and_values():
                    series_list.append({"labels": dict(key), "value": float(raw)})
            out[inst.name] = {
                "kind": inst.kind,
                "help": inst.help,
                "series": series_list,
            }
        return out

    def clear(self) -> None:
        with self._lock:
            self._instruments.clear()


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


class RunStats(dict):
    """Dict-compatible per-run stats view (drop-in for the old module-level
    `LAST_RUN_STATS = {}` accumulators, which lint rule STX002 now forbids).
    Producers publish to the metrics registry during the run and refresh this
    view once at the end; consumers (bench.py, tests) keep plain dict reads."""
