"""Unified telemetry for both Podracer architectures (docs/DESIGN.md §2.2).

Three pillars, all zero-dependency and off by default:

  * **Tracing** (trace.py / trace_export.py): host-side `span()` context
    managers — thread-aware, monotonic-clock — exported as Chrome-trace/
    Perfetto JSON so host threads load alongside the `jax.profiler` device
    trace. `annotate()` tags jitted code at epoch/minibatch boundaries.
  * **Metrics** (registry.py / exporters.py): process-wide counters, gauges,
    and histograms with labels, snapshot-on-demand, Prometheus text
    exposition + JSONL sinks. `RunStats` is the dict-compatible per-run view
    that replaced the ad-hoc module-level stats dicts (lint STX002).
  * **Introspection** (introspect.py / health.py): a device-telemetry poller
    (memory_stats, live buffers) sampled off the hot path, plus Sebulba
    heartbeats and a stall detector that names the starved component.

`configure(cfg.logger.telemetry)` is the single switch — called by
StoixLogger on construction. Disabled (the default), spans are shared no-op
context managers, no poller thread starts, and no files are written: behavior
is bit-identical to a build without telemetry (tests/test_observability.py
pins this) and PR 1's pipelined-loop no-host-sync guarantees are untouched —
every instrument here is host-memory only.
"""

from __future__ import annotations

import logging
import sys
import threading
from typing import Any, Optional

from stoix_tpu.observability.exporters import (  # noqa: F401 — public API
    JsonlMetricsWriter,
    flatten_snapshot,
    to_prometheus_text,
    write_prometheus,
)
from stoix_tpu.observability.aggregate import (  # noqa: F401
    FleetMetricsAggregator,
    aggregator_from_fleet,
)
from stoix_tpu.observability.flightrec import (  # noqa: F401
    FlightRecorder,
    dump_flight_record,
    get_flight_recorder,
    validate_flight_record,
)
from stoix_tpu.observability.goodput import (  # noqa: F401
    GoodputLedger,
)
from stoix_tpu.observability.health import (  # noqa: F401
    ActorStarvationError,
    HealthMonitor,
    HeartbeatBoard,
    StallDetector,
    get_health_monitor,
)
from stoix_tpu.observability.httpz import (  # noqa: F401
    OpsServer,
    StatusBoard,
    get_status_board,
    render_statusz,
    server_from_config,
)
from stoix_tpu.observability.introspect import (  # noqa: F401
    DeviceTelemetryPoller,
    sample_device_telemetry,
)
from stoix_tpu.observability.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RunStats,
    get_registry,
)
from stoix_tpu.observability.trace import (  # noqa: F401
    annotate,
    device_annotation,
    get_recorder,
    instant,
    is_enabled,
    set_enabled,
    span,
)
from stoix_tpu.observability.trace_export import (  # noqa: F401
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)

_lock = threading.Lock()
_poller: Optional[DeviceTelemetryPoller] = None
_http_server: Optional[OpsServer] = None


def get_logger(name: str = "stoix_tpu") -> logging.Logger:
    """Library status-line logger. Library code uses this instead of bare
    print() — lint rule STX002 — so stdout stays reserved for machine-readable
    output contracts (bench.py, sweep.py) and the ConsoleSink.

    Defers to the application's logging config when one exists: if the root
    logger (or the 'stoix_tpu' logger itself) already has handlers, nothing
    is attached and records propagate normally. Only in the bare-CLI case —
    no handlers anywhere — does this attach a message-only stderr handler at
    INFO (the behavior the old print() calls had). Call this at the log
    site, not at module import, so an app's logging.basicConfig() wins."""
    root = logging.getLogger("stoix_tpu")
    with _lock:
        if not root.handlers and not logging.getLogger().handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter("%(message)s"))
            root.addHandler(handler)
            root.setLevel(logging.INFO)
            root.propagate = False
    return logging.getLogger(name)


def configure(telemetry_cfg: Any = None) -> bool:
    """Apply a `logger.telemetry` config block (a plain/Config dict or None).
    Returns whether telemetry is enabled. Idempotent: reconfiguring replaces
    the poller (and the ops HTTP server); disabling stops them and turns
    span recording off. Output paths are the TelemetrySink's concern
    (utils/logger.py wires them).

    This is also the per-run reset seam for the ops plane (docs/DESIGN.md
    §2.13): every run start — supervised relaunch included — gets a fresh
    HealthMonitor (no stale heartbeat boards from the previous incarnation
    can trip an instant 503/stall verdict) and a fresh flight-recorder ring
    (a crash dump covers THIS run's windows, not the last run's). Both are
    host-memory resets: no device work, bit-identity untouched."""
    cfg = telemetry_cfg or {}
    enabled = bool(cfg.get("enabled", False))
    global _poller, _http_server
    with _lock:
        set_enabled(enabled)
        if _poller is not None:
            _poller.stop()
            _poller = None
        if _http_server is not None:
            _http_server.close()
            _http_server = None
        get_health_monitor().reset()
        get_flight_recorder().clear()
        # `logger.telemetry.http` is its own switch: the endpoints serve the
        # registry/health state that exists regardless of whether span/file
        # telemetry is on. Off by default = no socket, no thread.
        _http_server = server_from_config(cfg.get("http"))
        if enabled:
            # Fresh span buffer per enabled run: without this, a second
            # telemetry run in the same process would export the previous
            # run's spans too (the buffer survives shutdown() so the LAST
            # run stays exportable).
            get_recorder().clear()
            interval = float(cfg.get("device_poll_interval_s", 5.0) or 0.0)
            if interval > 0:
                _poller = DeviceTelemetryPoller(interval_s=interval)
                _poller.start()
            # Seed one synchronous sample so even short runs snapshot device
            # memory series (the poller's first tick is one interval away).
            sample_device_telemetry()
    return enabled


def shutdown() -> None:
    """Stop the poller and the ops HTTP server, and disable span recording
    (buffer/registry contents are kept — the caller may still export
    them)."""
    global _poller, _http_server
    with _lock:
        if _poller is not None:
            _poller.stop()
            _poller = None
        if _http_server is not None:
            _http_server.close()
            _http_server = None
        set_enabled(False)


def get_ops_server() -> Optional[OpsServer]:
    """The live OpsServer started by configure(), or None when
    `logger.telemetry.http.enabled` is off. Tests and the runner read the
    ephemeral port (`get_ops_server().port`) from here; the runner also
    attaches the fleet aggregator through it."""
    with _lock:
        return _http_server
