"""Unified telemetry for both Podracer architectures (docs/DESIGN.md §2.2).

Three pillars, all zero-dependency and off by default:

  * **Tracing** (trace.py / trace_export.py): host-side `span()` context
    managers — thread-aware, monotonic-clock — exported as Chrome-trace/
    Perfetto JSON so host threads load alongside the `jax.profiler` device
    trace. `annotate()` tags jitted code at epoch/minibatch boundaries.
  * **Metrics** (registry.py / exporters.py): process-wide counters, gauges,
    and histograms with labels, snapshot-on-demand, Prometheus text
    exposition + JSONL sinks. `RunStats` is the dict-compatible per-run view
    that replaced the ad-hoc module-level stats dicts (lint STX002).
  * **Introspection** (introspect.py / health.py): a device-telemetry poller
    (memory_stats, live buffers) sampled off the hot path, plus Sebulba
    heartbeats and a stall detector that names the starved component.

`configure(cfg.logger.telemetry)` is the single switch — called by
StoixLogger on construction. Disabled (the default), spans are shared no-op
context managers, no poller thread starts, and no files are written: behavior
is bit-identical to a build without telemetry (tests/test_observability.py
pins this) and PR 1's pipelined-loop no-host-sync guarantees are untouched —
every instrument here is host-memory only.
"""

from __future__ import annotations

import logging
import sys
import threading
from typing import Any, Optional

from stoix_tpu.observability.exporters import (  # noqa: F401 — public API
    JsonlMetricsWriter,
    flatten_snapshot,
    to_prometheus_text,
    write_prometheus,
)
from stoix_tpu.observability.health import (  # noqa: F401
    ActorStarvationError,
    HeartbeatBoard,
    StallDetector,
)
from stoix_tpu.observability.introspect import (  # noqa: F401
    DeviceTelemetryPoller,
    sample_device_telemetry,
)
from stoix_tpu.observability.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    RunStats,
    get_registry,
)
from stoix_tpu.observability.trace import (  # noqa: F401
    annotate,
    device_annotation,
    get_recorder,
    instant,
    is_enabled,
    set_enabled,
    span,
)
from stoix_tpu.observability.trace_export import (  # noqa: F401
    to_chrome_trace,
    validate_chrome_trace,
    write_chrome_trace,
)

_lock = threading.Lock()
_poller: Optional[DeviceTelemetryPoller] = None


def get_logger(name: str = "stoix_tpu") -> logging.Logger:
    """Library status-line logger. Library code uses this instead of bare
    print() — lint rule STX002 — so stdout stays reserved for machine-readable
    output contracts (bench.py, sweep.py) and the ConsoleSink.

    Defers to the application's logging config when one exists: if the root
    logger (or the 'stoix_tpu' logger itself) already has handlers, nothing
    is attached and records propagate normally. Only in the bare-CLI case —
    no handlers anywhere — does this attach a message-only stderr handler at
    INFO (the behavior the old print() calls had). Call this at the log
    site, not at module import, so an app's logging.basicConfig() wins."""
    root = logging.getLogger("stoix_tpu")
    with _lock:
        if not root.handlers and not logging.getLogger().handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter("%(message)s"))
            root.addHandler(handler)
            root.setLevel(logging.INFO)
            root.propagate = False
    return logging.getLogger(name)


def configure(telemetry_cfg: Any = None) -> bool:
    """Apply a `logger.telemetry` config block (a plain/Config dict or None).
    Returns whether telemetry is enabled. Idempotent: reconfiguring replaces
    the poller; disabling stops it and turns span recording off. Output
    paths are the TelemetrySink's concern (utils/logger.py wires them)."""
    cfg = telemetry_cfg or {}
    enabled = bool(cfg.get("enabled", False))
    global _poller
    with _lock:
        set_enabled(enabled)
        if _poller is not None:
            _poller.stop()
            _poller = None
        if enabled:
            # Fresh span buffer per enabled run: without this, a second
            # telemetry run in the same process would export the previous
            # run's spans too (the buffer survives shutdown() so the LAST
            # run stays exportable).
            get_recorder().clear()
            interval = float(cfg.get("device_poll_interval_s", 5.0) or 0.0)
            if interval > 0:
                _poller = DeviceTelemetryPoller(interval_s=interval)
                _poller.start()
            # Seed one synchronous sample so even short runs snapshot device
            # memory series (the poller's first tick is one interval away).
            sample_device_telemetry()
    return enabled


def shutdown() -> None:
    """Stop the poller and disable span recording (buffer/registry contents
    are kept — the caller may still export them)."""
    global _poller
    with _lock:
        if _poller is not None:
            _poller.stop()
            _poller = None
        set_enabled(False)
