"""Host-side span tracing: thread-aware, monotonic-clock, Chrome-trace ready.

`span("learn_dispatch")` records one complete event (Chrome trace `"ph": "X"`)
into a process-wide buffer when tracing is enabled; when disabled (the
default) it returns a shared no-op context manager — one boolean check, no
allocation — so hot loops can keep their spans unconditionally.

Timestamps come from `time.perf_counter_ns()` against a per-recorder epoch
(monotonic: wall-clock steps cannot reorder events), recorded in microseconds
— the Chrome trace-event unit — so the exported file (trace_export.py) lines
up with the `jax.profiler` device trace when both are loaded in Perfetto.

For code under `jax.jit`, use `annotate(name)` — a `jax.named_scope` — at
epoch/minibatch boundaries: it tags XLA ops so the device trace carries the
same taxonomy, and costs nothing at runtime.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List


class _NoopSpan:
    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: Any) -> None:
        return None


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_recorder", "_name", "_args", "_start")

    def __init__(self, recorder: "TraceRecorder", name: str, args: Dict[str, Any]):
        self._recorder = recorder
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._start = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._recorder._record(self._name, self._start, time.perf_counter_ns(), self._args)


class TraceRecorder:
    """Bounded in-memory buffer of complete span events.

    `max_events` caps memory for long runs (drops record a counter so the
    export can say how many were lost — silent truncation would read as
    "nothing else happened")."""

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._thread_names: Dict[int, str] = {}
        self._epoch_ns = time.perf_counter_ns()
        self._max_events = max_events
        self.dropped = 0
        self.enabled = False

    def span(self, name: str, **args: Any):
        if not self.enabled:
            return _NOOP
        return _Span(self, name, args)

    def _record(self, name: str, start_ns: int, end_ns: int, args: Dict[str, Any]) -> None:
        thread = threading.current_thread()
        tid = thread.ident or 0
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = thread.name
            if len(self._events) >= self._max_events:
                self.dropped += 1
                return
            self._events.append(
                {
                    "name": name,
                    "ts": (start_ns - self._epoch_ns) / 1e3,  # microseconds
                    "dur": (end_ns - start_ns) / 1e3,
                    "tid": tid,
                    "args": {k: _jsonable(v) for k, v in args.items()},
                }
            )

    def instant(self, name: str, **args: Any) -> None:
        """Zero-duration marker (exported as a Chrome instant event)."""
        if not self.enabled:
            return
        now = time.perf_counter_ns()
        self._record(name, now, now, args)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._thread_names)

    def event_count(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._thread_names.clear()
            self.dropped = 0
            self._epoch_ns = time.perf_counter_ns()


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


_RECORDER = TraceRecorder()


def get_recorder() -> TraceRecorder:
    return _RECORDER


def span(name: str, **args: Any):
    """Context manager timing one host-side phase. No-op unless tracing is
    enabled (observability.configure / set_enabled)."""
    return _RECORDER.span(name, **args)


def instant(name: str, **args: Any) -> None:
    _RECORDER.instant(name, **args)


def set_enabled(enabled: bool) -> None:
    _RECORDER.enabled = bool(enabled)


def is_enabled() -> bool:
    return _RECORDER.enabled


def annotate(name: str):
    """Taxonomy tag for code under jit: a `jax.named_scope`. Trace-time only
    — zero runtime cost — and surfaces the span name in the XLA/Perfetto
    device trace next to the host spans recorded here."""
    import jax

    return jax.named_scope(name)


def device_annotation(name: str, **kwargs: Any):
    """Host-thread annotation for the `jax.profiler` device trace (TraceMe):
    wraps dispatch sites so the device timeline names them too. Falls back to
    a no-op when the profiler is unavailable."""
    import jax

    try:
        return jax.profiler.TraceAnnotation(name, **kwargs)
    except Exception:  # noqa: BLE001 — profiling must never kill a run
        return _NOOP
