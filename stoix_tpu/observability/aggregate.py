"""Fleet-wide metrics aggregation over the fleet KV store
(docs/DESIGN.md §2.13).

Each host's publisher thread periodically serializes its registry snapshot
to JSON and `put`s it at `stoix_tpu/fleet/ometrics/<process_index>` through
the SAME backend protocol the fleet coordinator already speaks (fleet.py
JaxKVBackend / FakeFleetBackend) — one bounded blob per host per interval,
entirely off the training hot path. Host 0 (or any host, on demand) folds
the newest blob from every peer into one Prometheus text page with a
`host="<process_index>"` label on every series, served at `/metrics/fleet`
(httpz.py).

KV traffic bound: one value of ~64 bytes x series_count per host per
`interval_s` (a few KiB/s for a fully instrumented run at the 10 s default)
— documented with the protocol in DESIGN.md §2.13. Rendering reuses
exporters.py's formatting primitives; there is no second exposition-format
implementation.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional

from stoix_tpu.observability.exporters import _fmt_labels, _fmt_value
from stoix_tpu.observability.registry import MetricsRegistry, get_registry

# Key prefix INSIDE the fleet backend's own namespace (JaxKVBackend already
# prefixes "stoix_tpu/fleet/"): distinct from hb/, vote/, flag/ traffic.
_KEY_PREFIX = "ometrics/"


def encode_snapshot(snapshot: Dict[str, Any]) -> str:
    """JSON-safe encoding of `MetricsRegistry.snapshot()`: histogram bucket
    dicts keyed by float bounds become [bound, count] pair lists (JSON
    object keys must be strings; round-tripping through str would corrupt
    the +Inf bound)."""
    families: Dict[str, Any] = {}
    for name, family in snapshot.items():
        series_out: List[Dict[str, Any]] = []
        for series in family["series"]:
            entry: Dict[str, Any] = {"labels": dict(series["labels"])}
            if family["kind"] == "histogram":
                entry["summary"] = dict(series["summary"])
                entry["buckets"] = sorted(
                    [bound, count] for bound, count in series["buckets"].items()
                )
            else:
                entry["value"] = series["value"]
            series_out.append(entry)
        families[name] = {
            "kind": family["kind"],
            "help": family["help"],
            "series": series_out,
        }
    return json.dumps(families)


def decode_snapshot(blob: str) -> Dict[str, Any]:
    families = json.loads(blob)
    for family in families.values():
        if family["kind"] == "histogram":
            for series in family["series"]:
                series["buckets"] = {
                    float(bound): count for bound, count in series["buckets"]
                }
    return families


def render_fleet_text(snapshots: Dict[int, Dict[str, Any]]) -> str:
    """Fold per-host snapshots into one exposition page: every series gains
    a `host` label, `# HELP`/`# TYPE` still emitted once per family (first
    host's help text wins — the code is identical fleet-wide)."""
    merged: Dict[str, Dict[str, Any]] = {}
    for host in sorted(snapshots):
        for name, family in snapshots[host].items():
            slot = merged.setdefault(
                name, {"kind": family["kind"], "help": family["help"], "series": []}
            )
            for series in family["series"]:
                slot["series"].append((host, series))
    lines: List[str] = []
    for name, family in sorted(merged.items()):
        if family["help"]:
            lines.append(f"# HELP {name} {family['help']}")
        lines.append(f"# TYPE {name} {family['kind']}")
        for host, series in family["series"]:
            labels = series["labels"]
            host_label = {"host": str(host)}
            if family["kind"] == "histogram":
                for bound, count in sorted(series["buckets"].items()):
                    lines.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(labels, {**host_label, 'le': _fmt_value(bound)})}"
                        f" {count}"
                    )
                lines.append(
                    f"{name}_sum{_fmt_labels(labels, host_label)} "
                    f"{_fmt_value(series['summary']['sum'])}"
                )
                lines.append(
                    f"{name}_count{_fmt_labels(labels, host_label)} "
                    f"{series['summary']['count']}"
                )
            else:
                lines.append(
                    f"{name}{_fmt_labels(labels, host_label)} "
                    f"{_fmt_value(series['value'])}"
                )
    return "\n".join(lines) + "\n"


class FleetMetricsAggregator:
    """Publish this host's snapshot on a cadence; fold every host's newest
    blob on demand. `backend` speaks the fleet KV protocol (put/try_get) —
    the production JaxKVBackend or a FakeFleetBackend view in tests."""

    def __init__(
        self,
        backend: Any,
        process_index: int,
        num_processes: int,
        registry: Optional[MetricsRegistry] = None,
        interval_s: float = 10.0,
    ):
        self._backend = backend
        self._process_index = int(process_index)
        self._num_processes = int(num_processes)
        self._registry = registry or get_registry()
        self._interval_s = max(0.5, float(interval_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def publish_once(self) -> None:
        """One snapshot -> KV put. Overwrites the previous blob (the fold
        only ever wants the newest); size is bounded by the registry's live
        series count, never by run length."""
        blob = encode_snapshot(self._registry.snapshot())
        self._backend.put(f"{_KEY_PREFIX}{self._process_index}", blob)

    def _publisher_loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            self.publish_once()

    def start(self) -> "FleetMetricsAggregator":
        if self._thread is not None:
            return self
        self.publish_once()
        self._thread = threading.Thread(
            target=self._publisher_loop,
            name="stoix-tpu-metrics-aggregate",
            daemon=True,
        )
        self._thread.start()
        return self

    def render(self) -> str:
        """The fleet-wide /metrics page: this host's LIVE snapshot plus the
        newest published blob from every peer (a peer that has not published
        yet is simply absent — the page never blocks on the KV store)."""
        # decode(encode(...)) normalizes this host's live snapshot into the
        # same bucket-list-free shape the peers' decoded blobs have.
        snapshots: Dict[int, Dict[str, Any]] = {
            self._process_index: decode_snapshot(
                encode_snapshot(self._registry.snapshot())
            )
        }
        for peer in range(self._num_processes):
            if peer == self._process_index:
                continue
            blob = self._backend.try_get(f"{_KEY_PREFIX}{peer}")
            if blob is None:
                continue
            try:
                snapshots[peer] = decode_snapshot(blob)
            except (ValueError, KeyError, TypeError):
                continue  # torn/old blob: skip this peer for this render
        return render_fleet_text(snapshots)

    def close(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(join_timeout)


def aggregator_from_fleet(
    fleet_coord: Any, interval_s: float = 10.0
) -> Optional[FleetMetricsAggregator]:
    """Build an aggregator riding an active FleetCoordinator's KV backend.
    None when the coordinator has no backend (single-process fleet) — the
    local /metrics page already tells the whole story there."""
    backend = getattr(fleet_coord, "_backend", None)
    if backend is None:
        return None
    return FleetMetricsAggregator(
        backend,
        process_index=int(fleet_coord.process_index),
        num_processes=int(fleet_coord.process_count),
        interval_s=interval_s,
    )
