"""Sebulba health: heartbeats per component and a stall detector that NAMES
the starved side instead of surfacing an anonymous `queue.Empty`.

Every Sebulba component (actor-i, learner, param-server, evaluator) beats a
`HeartbeatBoard` each time it completes a unit of work. When the learner's
rollout collection times out, `diagnose()` turns heartbeat ages into a
verdict: the actor that stopped beating is dead/starved; an actor that IS
beating while the learner times out means the pipeline hand-off is wedged;
a stale param-server beat means actors are starved of fresh params upstream.

Ages also export as gauges (`stoix_tpu_sebulba_heartbeat_age_seconds{component=...}`)
so a registry snapshot taken during a live stall shows the same story.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from stoix_tpu.observability.registry import MetricsRegistry, get_registry


class HeartbeatBoard:
    """Monotonic last-beat timestamps per component name; thread-safe."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._lock = threading.Lock()
        self._beats: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._registry = registry or get_registry()
        self._beat_counter = self._registry.counter(
            "stoix_tpu_sebulba_heartbeats_total",
            "Completed work units per Sebulba component",
        )

    def beat(self, component: str) -> None:
        now = time.monotonic()
        with self._lock:
            self._beats[component] = now
            self._counts[component] = self._counts.get(component, 0) + 1
        self._beat_counter.inc(labels={"component": component})

    def age(self, component: str) -> Optional[float]:
        """Seconds since the last beat, or None if it never beat."""
        with self._lock:
            last = self._beats.get(component)
        return None if last is None else time.monotonic() - last

    def count(self, component: str) -> int:
        with self._lock:
            return self._counts.get(component, 0)

    def ages(self) -> Dict[str, Optional[float]]:
        with self._lock:
            beats = dict(self._beats)
        now = time.monotonic()
        return {k: now - v for k, v in beats.items()}

    def export_ages(self) -> None:
        gauge = self._registry.gauge(
            "stoix_tpu_sebulba_heartbeat_age_seconds",
            "Seconds since each Sebulba component last completed work",
        )
        for component, age in self.ages().items():
            gauge.set(age, {"component": component})

    def reset(self) -> None:
        """Forget all last-beat timestamps. A supervised relaunch (or a
        second run in the same process) must start from a board with NO
        history: stale beats from the previous incarnation would otherwise
        read as an instant stall verdict (docs/DESIGN.md §2.13)."""
        with self._lock:
            self._beats.clear()
            self._counts.clear()


def describe_age(age: Optional[float]) -> str:
    return "never beat" if age is None else f"last beat {age:.1f}s ago"


class StallDetector:
    """Heartbeat-age verdicts. `stale_after_s` is the age beyond which a
    component counts as stalled (defaults to half the collect timeout the
    caller passes to diagnose sites)."""

    def __init__(self, board: HeartbeatBoard, stale_after_s: float = 30.0):
        self.board = board
        self.stale_after_s = float(stale_after_s)

    def diagnose(self, waiting_on: Optional[str] = None) -> str:
        """One-line verdict naming the starved component. `waiting_on` is the
        component the caller timed out waiting FOR (e.g. "actor-3")."""
        self.board.export_ages()
        ages = self.board.ages()
        if waiting_on is not None:
            age = ages.get(waiting_on)
            if age is None:
                return (
                    f"{waiting_on} never produced work — it likely crashed "
                    f"during setup (check its thread's traceback)"
                )
            if age > self.stale_after_s:
                return (
                    f"{waiting_on} stalled ({describe_age(age)}): it stopped "
                    f"producing — dead env backend or starved of params"
                )
            return (
                f"{waiting_on} is alive ({describe_age(age)}) but its hand-off "
                f"queue did not deliver — pipeline wedged (consumer not "
                f"draining, or payload stuck in device transfer)"
            )
        stalled = {
            k: v for k, v in ages.items() if v is not None and v > self.stale_after_s
        }
        if not stalled:
            return "all components beating within threshold"
        worst = max(stalled, key=lambda k: stalled[k])
        return f"{worst} stalled ({describe_age(stalled[worst])})"


class HealthMonitor:
    """Process-wide aggregation of liveness sources for `/healthz`
    (docs/DESIGN.md §2.13): heartbeat boards (runner window loop, Sebulba
    pipelines) judged through StallDetector thresholds, arbitrary check
    callables (serve worker liveness), and the watchdog stage verdict (any
    `stoix_tpu_watchdog_stalls_total` increment since the run started).

    `reset()` is the supervised-relaunch seam: `observability.configure()`
    calls it on every run start, so a fresh incarnation begins with no
    boards, no checks, and a re-based watchdog counter — stale state from
    the previous run can never trip an instant 503."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._registry = registry or get_registry()
        self._lock = threading.Lock()
        self._boards: Dict[str, Tuple[HeartbeatBoard, float]] = {}
        self._checks: Dict[str, Callable[[], Optional[str]]] = {}
        self._stall_base = self._watchdog_stalls()

    def _watchdog_stalls(self) -> float:
        counter = self._registry.counter(
            "stoix_tpu_watchdog_stalls_total",
            "Watchdog deadline expirations, by stage",
        )
        return float(sum(value for _, value in counter.labels_and_values()))

    def register_board(
        self, name: str, board: HeartbeatBoard, stale_after_s: float = 60.0
    ) -> None:
        with self._lock:
            self._boards[name] = (board, float(stale_after_s))

    def register_check(
        self, name: str, check: Callable[[], Optional[str]]
    ) -> None:
        """`check()` returns None when healthy, else a one-line problem."""
        with self._lock:
            self._checks[name] = check

    def unregister(self, name: str) -> None:
        with self._lock:
            self._boards.pop(name, None)
            self._checks.pop(name, None)

    def reset(self) -> None:
        with self._lock:
            self._boards.clear()
            self._checks.clear()
        self._stall_base = self._watchdog_stalls()

    def verdict(self) -> Tuple[bool, str]:
        """(healthy, one-page detail). Unhealthy when any registered board
        has a component older than its threshold, any check reports a
        problem, or a watchdog stage blew its deadline this run. A component
        that never beat is NOT unhealthy — compile/warmup precedes the first
        beat and must not read as a stall."""
        with self._lock:
            boards = dict(self._boards)
            checks = dict(self._checks)
        problems: List[str] = []
        lines: List[str] = []
        for name, (board, stale_after_s) in sorted(boards.items()):
            detector = StallDetector(board, stale_after_s=stale_after_s)
            ages = board.ages()
            stalled = sorted(
                component
                for component, age in ages.items()
                if age is not None and age > stale_after_s
            )
            if stalled:
                problems.append(f"{name}: {detector.diagnose()}")
            summary = ", ".join(
                f"{component}={describe_age(age)}"
                for component, age in sorted(ages.items())
            )
            lines.append(f"{name}: {summary or 'no beats yet'}")
        for name, check in sorted(checks.items()):
            problem = check()
            if problem is not None:
                problems.append(f"{name}: {problem}")
            lines.append(f"{name}: {problem or 'ok'}")
        stalls = self._watchdog_stalls() - self._stall_base
        if stalls > 0:
            problems.append(
                f"watchdog: {int(stalls)} stage deadline(s) blown this run"
            )
        if problems:
            return False, "\n".join(problems)
        return True, "ok\n" + "\n".join(lines) if lines else "ok"


_monitor_lock = threading.Lock()
_monitor: Optional[HealthMonitor] = None


def get_health_monitor() -> HealthMonitor:
    """Process-wide monitor serving `/healthz` (httpz.py)."""
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = HealthMonitor()
        return _monitor


class ActorStarvationError(RuntimeError):
    """Raised by OnPolicyPipeline.collect_rollouts in place of a bare
    queue.Empty: carries WHICH actor timed out and the heartbeat verdict."""

    def __init__(self, actor_id: int, timeout: float, verdict: str,
                 age: Optional[float]):
        self.actor_id = actor_id
        self.heartbeat_age = age
        super().__init__(
            f"collect_rollouts timed out after {timeout:.0f}s waiting for "
            f"actor-{actor_id} ({describe_age(age)}): {verdict}"
        )
