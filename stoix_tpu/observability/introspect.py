"""Device/runtime introspection sampled OFF the hot path.

A daemon thread polls, per local device, `device.memory_stats()` (PJRT
metadata queries — they read allocator counters, they do not join the device
stream, so polling never stalls a dispatched program) plus the process-wide
live-buffer count (`jax.live_arrays()`), publishing gauges:

    stoix_tpu_device_memory_bytes{device=..., kind=bytes_in_use|peak_bytes_in_use|...,
                                  source=memory_stats|live_buffer_sum}
    stoix_tpu_device_live_buffers{}
    stoix_tpu_device_poll_errors_total{}

Cumulative XLA compile time is a registry counter
(`stoix_tpu_runner_compile_seconds_total`) fed by the Anakin runner's AOT
warmup phase — the poller only samples what the runtime exposes.

CPU backends expose no `memory_stats()` (returns None / raises): for those,
`bytes_in_use` is estimated by summing live-buffer nbytes per device (source
label `live_buffer_sum`), so every backend still produces memory series.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from stoix_tpu.observability.registry import MetricsRegistry, get_registry

# memory_stats() keys worth a series (backend-dependent; absent keys skipped).
_MEMORY_KINDS = (
    "bytes_in_use",
    "peak_bytes_in_use",
    "bytes_limit",
    "largest_alloc_size",
    "num_allocs",
)


def sample_device_telemetry(registry: Optional[MetricsRegistry] = None) -> int:
    """One synchronous sample (also the poller's body); returns the number of
    memory series updated. Safe to call from tests without a thread."""
    import jax

    registry = registry or get_registry()
    mem_gauge = registry.gauge(
        "stoix_tpu_device_memory_bytes",
        "Per-device allocator stats from PJRT memory_stats()",
    )
    buf_gauge = registry.gauge(
        "stoix_tpu_device_live_buffers",
        "Live jax.Array count in this process (jax.live_arrays)",
    )
    err_counter = registry.counter(
        "stoix_tpu_device_poll_errors_total",
        "Introspection sampling errors (backend gaps count once per poll)",
    )
    updated = 0
    try:
        devices: List[Any] = jax.local_devices()
    except Exception:  # noqa: BLE001 — backend not initialized yet
        err_counter.inc()
        return 0
    statless = []
    for device in devices:
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — CPU/older plugins: no stats
            stats = None
        if not stats:
            statless.append(device)
            continue
        label_dev = str(device)
        for kind in _MEMORY_KINDS:
            if kind in stats:
                mem_gauge.set(
                    float(stats[kind]),
                    {"device": label_dev, "kind": kind, "source": "memory_stats"},
                )
                updated += 1
    try:
        live = jax.live_arrays()
        buf_gauge.set(float(len(live)))
        if statless:
            # Backend exposes no allocator stats (CPU): estimate bytes in use
            # from live buffers, splitting replicated arrays across devices.
            in_use = {str(d): 0.0 for d in statless}
            for arr in live:
                try:
                    arr_devices = [str(d) for d in arr.devices()]
                    per_device = arr.nbytes / max(1, len(arr_devices))
                except Exception:  # noqa: BLE001 — deleted/exotic arrays
                    continue
                for d in arr_devices:
                    if d in in_use:
                        in_use[d] += per_device
            for d, nbytes in in_use.items():
                mem_gauge.set(
                    nbytes,
                    {"device": d, "kind": "bytes_in_use", "source": "live_buffer_sum"},
                )
                updated += 1
    except Exception:  # noqa: BLE001 — private-ish API; never fatal
        err_counter.inc()
    return updated


class DeviceTelemetryPoller:
    """Daemon polling thread; `interval_s <= 0` disables it entirely."""

    def __init__(self, interval_s: float = 5.0,
                 registry: Optional[MetricsRegistry] = None):
        self._interval = float(interval_s)
        self._registry = registry or get_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._interval <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="device-telemetry", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            sample_device_telemetry(self._registry)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
