"""Crash flight recorder: a bounded ring of per-window event records that the
resilience excepthooks dump as `flight_record.json` next to their quarantine /
rescue artifacts (docs/DESIGN.md §2.13).

When a host dies with rc 86 (watchdog stall), 87 (fleet partition) or 88
(state corruption), the quarantine record and the emergency checkpoint say
WHAT was decided — but not what the last N windows looked like on the way
down. The recorder keeps exactly that: each completed window appends one
small host-side dict (phase breakdown, fleet flags, fingerprint verdicts,
staleness, skew), and `dump_flight_record()` — called from the excepthook
paths in resilience/{watchdog,fleet,integrity}.py — serializes the ring
atomically so a post-mortem has the trajectory into the crash, not just the
final stack.

Recording is host-memory only (a lock + deque append, no device work, no
threads): it is always on and cannot perturb the training trajectory, so the
`logger.telemetry.http` bit-identity pin holds with the recorder active.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional

SCHEMA_VERSION = 1

# Default directory for dump sites that have no better-scoped artifact
# location (the watchdog's rc-86 path): matches the quarantine default
# (`checkpoints/quarantine.json`) so every crash artifact lands together.
_DEFAULT_DUMP_DIR = "checkpoints"

FLIGHT_RECORD_FILENAME = "flight_record.json"


class FlightRecorder:
    """Thread-safe bounded ring of event dicts. `capacity` bounds memory:
    a record is ~a few hundred bytes, so the default keeps the last 64
    windows for well under 100 KiB."""

    def __init__(self, capacity: int = 64):
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = collections.deque(maxlen=int(capacity))
        self._context: Dict[str, Any] = {}
        self._seq = 0

    def set_context(self, **fields: Any) -> None:
        """Run-level fields (run id, architecture, system) merged into every
        dump's header — set once at run start, survives `clear()` of events
        only via re-set (a fresh run re-stamps its own context)."""
        with self._lock:
            self._context.update(fields)

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event. `kind` names the record class ("window",
        "fault", "actor_crash", "integrity_verdict", ...)."""
        with self._lock:
            self._seq += 1
            event = {"seq": self._seq, "unix_time": time.time(), "kind": str(kind)}
            event.update(fields)
            self._events.append(event)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        """Fresh ring AND fresh context (per-run reset: a supervised
        relaunch / second in-process run must not dump the previous
        incarnation's windows as its own)."""
        with self._lock:
            self._events.clear()
            self._context.clear()
            self._seq = 0

    def dump(
        self, path: str, reason: str, exit_code: Optional[int] = None
    ) -> str:
        """Serialize the ring to `path` atomically (tmp + rename — a crash
        mid-dump never leaves a half-written record)."""
        with self._lock:
            record = {
                "version": SCHEMA_VERSION,
                "reason": str(reason),
                "exit_code": exit_code,
                "unix_time": time.time(),
                "context": dict(self._context),
                "events": list(self._events),
            }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=2, default=str)
        os.replace(tmp, path)
        return path


_lock = threading.Lock()
_recorder: Optional[FlightRecorder] = None


def get_flight_recorder() -> FlightRecorder:
    """Process-wide recorder (every subsystem appends to the same ring — a
    crash dump interleaves runner windows with supervisor/fault events in
    seq order)."""
    global _recorder
    with _lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def dump_flight_record(
    directory: Optional[str], reason: str, exit_code: Optional[int] = None
) -> Optional[str]:
    """Dump the process recorder as `<directory>/flight_record.json`.

    This is the excepthook entry point (fleet rc-87 → emergency_dir,
    integrity rc-88 → the quarantine file's directory, watchdog rc-86 →
    the default artifact dir): it must never raise on a path already going
    down, so filesystem failures degrade to None."""
    directory = directory or _DEFAULT_DUMP_DIR
    path = os.path.join(directory, FLIGHT_RECORD_FILENAME)
    try:
        return get_flight_recorder().dump(path, reason, exit_code)
    except OSError:
        return None


def validate_flight_record(record: Any) -> List[str]:
    """Schema check for tests/post-mortem tooling: [] means valid, otherwise
    a list of human-readable problems."""
    problems: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    if record.get("version") != SCHEMA_VERSION:
        problems.append(f"version {record.get('version')!r} != {SCHEMA_VERSION}")
    if not isinstance(record.get("reason"), str) or not record.get("reason"):
        problems.append("reason missing or empty")
    exit_code = record.get("exit_code")
    if exit_code is not None and not isinstance(exit_code, int):
        problems.append(f"exit_code {exit_code!r} is not int/None")
    if not isinstance(record.get("unix_time"), (int, float)):
        problems.append("unix_time missing")
    if not isinstance(record.get("context"), dict):
        problems.append("context missing or not a dict")
    events = record.get("events")
    if not isinstance(events, list):
        problems.append("events missing or not a list")
        return problems
    last_seq = 0
    for i, event in enumerate(events):
        if not isinstance(event, dict):
            problems.append(f"events[{i}] is not a dict")
            continue
        for field, kinds in (("seq", (int,)), ("unix_time", (int, float)),
                             ("kind", (str,))):
            if not isinstance(event.get(field), kinds):
                problems.append(f"events[{i}].{field} missing or wrong type")
        seq = event.get("seq")
        if isinstance(seq, int):
            if seq <= last_seq:
                problems.append(f"events[{i}].seq {seq} not strictly increasing")
            last_seq = seq
    return problems
