"""Per-process ops endpoints: /metrics, /healthz, /statusz, /varz
(docs/DESIGN.md §2.13).

A stdlib-only `ThreadingHTTPServer` on a daemon thread, started by
`observability.configure()` when `logger.telemetry.http.enabled` is true
(off by default: no socket, no thread, bit-identical — the pin lives in
tests/test_opsplane.py). Routes:

  /metrics   live Prometheus text straight from the process registry —
             `exporters.to_prometheus_text`, byte-compatible with the file
             the TelemetrySink writes (no second format code path)
  /metrics/fleet  host-0 fleet-wide view with per-host labels, when a
             FleetMetricsAggregator is attached (aggregate.py); 404 otherwise
  /healthz   HealthMonitor verdict (heartbeat boards + StallDetector
             thresholds + watchdog stage verdict): 200 ok / 503 detail
  /statusz   human one-page run status (StatusBoard + registry-derived
             phase/goodput/fleet/impact/replay sections)
  /varz      the same, as JSON ({"status": ..., "metrics": flat registry})

Requests read point-in-time snapshots (the registry copies under its own
locks); nothing on the training hot path ever blocks on this server. The
server thread is a daemon with an explicit `close()` shutdown+join path
(lint STX017's sanctioned lifecycle).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from stoix_tpu.observability import flightrec
from stoix_tpu.observability.exporters import flatten_snapshot, to_prometheus_text
from stoix_tpu.observability.health import HealthMonitor, get_health_monitor
from stoix_tpu.observability.registry import MetricsRegistry, get_registry


class StatusBoard:
    """Thread-safe run-status fields for /statusz and /varz. Producers
    (runner, Sebulba learner, serve) set plain values; `register_provider`
    attaches a zero-arg callable evaluated at render time (the serve SLO
    ladder stays live without the server pushing on every request)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._fields: Dict[str, Any] = {}
        self._providers: Dict[str, Callable[[], Any]] = {}

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._fields[key] = value

    def update(self, fields: Dict[str, Any]) -> None:
        with self._lock:
            self._fields.update(fields)

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._fields.get(key, default)

    def register_provider(self, key: str, provider: Callable[[], Any]) -> None:
        with self._lock:
            self._providers[key] = provider

    def unregister_provider(self, key: str) -> None:
        with self._lock:
            self._providers.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._fields.clear()
            self._providers.clear()

    def as_dict(self) -> Dict[str, Any]:
        with self._lock:
            fields = dict(self._fields)
            providers = dict(self._providers)
        for key, provider in providers.items():
            try:
                fields[key] = provider()
            except Exception as err:  # noqa: BLE001 — a broken provider must
                # not take down the status page reporting everything else.
                fields[key] = f"<provider error: {err!r}>"
        return fields


_board_lock = threading.Lock()
_status_board: Optional[StatusBoard] = None


def get_status_board() -> StatusBoard:
    global _status_board
    with _board_lock:
        if _status_board is None:
            _status_board = StatusBoard()
        return _status_board


def _section(title: str, rows: Dict[str, Any]) -> str:
    lines = [f"== {title} =="]
    for key, value in rows.items():
        lines.append(f"  {key:<28} {value}")
    return "\n".join(lines)


def render_statusz(
    status: StatusBoard, registry: Optional[MetricsRegistry] = None
) -> str:
    """One text page: everything an operator curls first. Pulls the status
    board (run identity, window/step, restore report) and derives the rest
    from the live registry snapshot so the page needs no extra bookkeeping
    on the hot path."""
    registry = registry or get_registry()
    fields = status.as_dict()
    flat = flatten_snapshot(registry.snapshot())
    page = [
        "stoix_tpu statusz",
        time.strftime("%Y-%m-%d %H:%M:%S %z"),
        "",
    ]

    run_rows = {
        key: fields[key]
        for key in ("run_id", "architecture", "system", "env")
        if key in fields
    }
    run_rows.update(
        {
            key: fields[key]
            for key in ("window", "step", "steps_per_second")
            if key in fields
        }
    )
    page.append(_section("run", run_rows or {"state": "no run registered"}))

    phase_rows = {
        key.split("phase=", 1)[1].rstrip("}"): f"{value:.3f}s"
        for key, value in sorted(flat.items())
        if key.startswith("stoix_tpu_runner_phase_seconds_total{")
    }
    if phase_rows:
        page.append(_section("phase breakdown (cumulative)", phase_rows))

    goodput_rows = {
        key.split("phase=", 1)[1].rstrip("}"): f"{value:.3f}s"
        for key, value in sorted(flat.items())
        if key.startswith("stoix_tpu_goodput_seconds_total{")
    }
    if "stoix_tpu_goodput_fraction" in flat:
        goodput_rows["goodput_fraction"] = f"{flat['stoix_tpu_goodput_fraction']:.4f}"
    if goodput_rows:
        page.append(_section("goodput ledger", goodput_rows))

    fleet_rows = {
        key[len("stoix_tpu_fleet_"):]: value
        for key, value in sorted(flat.items())
        if key.startswith("stoix_tpu_fleet_")
    }
    if fleet_rows:
        page.append(_section("fleet (skew / heartbeats)", fleet_rows))

    impact_rows = {
        key[len("stoix_tpu_impact_"):]: value
        for key, value in sorted(flat.items())
        if key.startswith("stoix_tpu_impact_")
    }
    if impact_rows:
        page.append(_section("impact staleness", impact_rows))

    replay_rows = {
        key[len("stoix_tpu_replay_"):]: value
        for key, value in sorted(flat.items())
        if key.startswith("stoix_tpu_replay_")
    }
    if replay_rows:
        page.append(_section("replay occupancy", replay_rows))

    resilience_rows: Dict[str, Any] = {}
    if "restore_skipped" in fields:
        resilience_rows["restore_skipped"] = fields["restore_skipped"]
    restore_report = fields.get("last_restore_report")
    if restore_report:
        for i, entry in enumerate(restore_report):
            resilience_rows[f"restore_report[{i}]"] = entry
    quarantine_file = fields.get("quarantine_file")
    if quarantine_file and os.path.exists(str(quarantine_file)):
        resilience_rows["quarantine_record"] = quarantine_file
    if resilience_rows:
        page.append(_section("resilience", resilience_rows))

    serve_slo = fields.get("serve_slo")
    if isinstance(serve_slo, dict):
        page.append(
            _section("serve SLO ladder", {k: serve_slo[k] for k in sorted(serve_slo)})
        )

    events = flightrec.get_flight_recorder().events()
    if events:
        last = events[-1]
        page.append(
            _section(
                "flight recorder",
                {
                    "events_buffered": len(events),
                    "last_event": f"{last.get('kind')} (seq {last.get('seq')})",
                },
            )
        )
    return "\n\n".join(page) + "\n"


class _OpsHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # Set by OpsServer.start(); the handler reaches its owner through the
    # server instance http.server passes it.
    ops: "OpsServer"


class _Handler(BaseHTTPRequestHandler):
    server: _OpsHTTPServer

    def _respond(self, code: int, body: str, content_type: str) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self) -> None:  # noqa: N802 — http.server API name
        ops = self.server.ops
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            route = ops.routes.get(path)
            if route is None:
                self._respond(
                    404,
                    "not found; endpoints: " + ", ".join(sorted(ops.routes)) + "\n",
                    "text/plain; charset=utf-8",
                )
                return
            code, body, content_type = route()
            self._respond(code, body, content_type)
        except BrokenPipeError:
            pass  # client hung up mid-response; nothing to answer
        except Exception as err:  # noqa: BLE001 — an endpoint bug must return
            # 500 to the scraper, never kill the handler thread with a
            # traceback dump to stderr on every poll.
            try:
                self._respond(500, f"internal error: {err!r}\n",
                              "text/plain; charset=utf-8")
            except OSError:
                pass

    def log_message(self, format: str, *args: Any) -> None:  # noqa: A002
        # Route http.server's per-request stderr lines to debug logging:
        # a 1 Hz scraper must not spam an interactive run's console.
        logging.getLogger("stoix_tpu.httpz").debug(format, *args)


class OpsServer:
    """The per-process ops-plane HTTP server. `start()` binds (port 0 picks
    an ephemeral port — read `.port` after start) and serves from a daemon
    thread; `close()` shuts the socket down and joins the thread."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        registry: Optional[MetricsRegistry] = None,
        monitor: Optional[HealthMonitor] = None,
        status: Optional[StatusBoard] = None,
    ):
        self._host = host
        self._port = int(port)
        self._registry = registry or get_registry()
        self._monitor = monitor or get_health_monitor()
        self._status = status or get_status_board()
        self._server: Optional[_OpsHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._aggregator: Optional[Any] = None
        self.routes: Dict[str, Callable[[], Tuple[int, str, str]]] = {
            "/metrics": self._metrics,
            "/metrics/fleet": self._metrics_fleet,
            "/healthz": self._healthz,
            "/statusz": self._statusz,
            "/varz": self._varz,
        }

    def set_aggregator(self, aggregator: Optional[Any]) -> None:
        """Attach/detach the fleet metrics aggregator serving /metrics/fleet
        (aggregate.py — created per run when fleet coordination is on)."""
        self._aggregator = aggregator

    def _metrics(self) -> Tuple[int, str, str]:
        return (
            200,
            to_prometheus_text(self._registry),
            "text/plain; version=0.0.4; charset=utf-8",
        )

    def _metrics_fleet(self) -> Tuple[int, str, str]:
        aggregator = self._aggregator
        if aggregator is None:
            return (
                404,
                "no fleet aggregator attached (single-host run, or "
                "arch.fleet.enabled=false)\n",
                "text/plain; charset=utf-8",
            )
        return (
            200,
            aggregator.render(),
            "text/plain; version=0.0.4; charset=utf-8",
        )

    def _healthz(self) -> Tuple[int, str, str]:
        healthy, detail = self._monitor.verdict()
        return (200 if healthy else 503, detail + "\n", "text/plain; charset=utf-8")

    def _statusz(self) -> Tuple[int, str, str]:
        return (
            200,
            render_statusz(self._status, self._registry),
            "text/plain; charset=utf-8",
        )

    def _varz(self) -> Tuple[int, str, str]:
        healthy, detail = self._monitor.verdict()
        body = json.dumps(
            {
                "status": self._status.as_dict(),
                "healthy": healthy,
                "health_detail": detail,
                "metrics": flatten_snapshot(self._registry.snapshot()),
            },
            default=str,
            indent=2,
        )
        return 200, body + "\n", "application/json"

    def start(self) -> "OpsServer":
        if self._server is not None:
            return self
        server = _OpsHTTPServer((self._host, self._port), _Handler)
        server.ops = self
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever,
            name="stoix-tpu-httpz",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        if self._server is not None:
            return self._server.server_address[1]
        return self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def close(self, join_timeout: float = 5.0) -> None:
        server, thread = self._server, self._thread
        self._server, self._thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(join_timeout)


def server_from_config(http_cfg: Any) -> Optional[OpsServer]:
    """Build + start an OpsServer from a `logger.telemetry.http` block
    (plain/Config dict or None). Returns None when disabled — the off path
    creates no socket and no thread."""
    cfg = dict(http_cfg or {})
    if not bool(cfg.get("enabled", False)):
        return None
    server = OpsServer(
        host=str(cfg.get("host") or "127.0.0.1"),
        port=int(cfg.get("port") or 0),
    ).start()
    logging.getLogger("stoix_tpu.httpz").info(
        "[httpz] ops endpoints live at %s/{metrics,healthz,statusz,varz}",
        server.url,
    )
    return server
