"""Chrome trace-event / Perfetto JSON export for the host-side span buffer.

The output is the JSON-object form of the Chrome trace-event format
(`{"traceEvents": [...]}`), which Perfetto and chrome://tracing both load.
Span events are complete events (`"ph": "X"`, microsecond `ts`/`dur`), sorted
by `ts`; thread-name metadata events (`"ph": "M"`) label each host thread
(actor-0, learner, async-evaluator, ...). Loading this file TOGETHER with the
`jax.profiler` trace of the same run (see docs/DESIGN.md §2.2) puts host
threads alongside the device timeline in one Perfetto view.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from stoix_tpu.observability.trace import TraceRecorder, get_recorder

# Single-process runs: one pid keeps all host threads in one Perfetto group.
_PID = os.getpid()


def to_chrome_trace(recorder: Optional[TraceRecorder] = None) -> Dict[str, Any]:
    recorder = recorder or get_recorder()
    events: List[Dict[str, Any]] = []
    for tid, name in sorted(recorder.thread_names().items()):
        events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": _PID,
                "tid": tid,
                "args": {"name": name},
            }
        )
    spans = sorted(recorder.events(), key=lambda e: e["ts"])
    for e in spans:
        event = {
            "name": e["name"],
            "ph": "X",
            "ts": e["ts"],
            "dur": e["dur"],
            "pid": _PID,
            "tid": e["tid"],
        }
        if e["args"]:
            event["args"] = e["args"]
        events.append(event)
    trace: Dict[str, Any] = {"traceEvents": events, "displayTimeUnit": "ms"}
    if recorder.dropped:
        trace["metadata"] = {"dropped_events": recorder.dropped}
    return trace


def write_chrome_trace(path: str, recorder: Optional[TraceRecorder] = None) -> str:
    """Write the trace JSON; returns the path for log lines."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(to_chrome_trace(recorder), f)
    return path


def validate_chrome_trace(trace: Dict[str, Any]) -> List[str]:
    """Schema check used by tests and the telemetry self-check: returns a
    list of violations (empty = valid). Checks the invariants Perfetto
    actually relies on: every event has name/ph/pid/tid, complete events have
    numeric non-negative ts/dur, and complete events are ts-sorted."""
    problems: List[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    last_ts = None
    for i, e in enumerate(events):
        for field in ("name", "ph", "pid", "tid"):
            if field not in e:
                problems.append(f"event {i}: missing {field}")
        ph = e.get("ph")
        if ph not in ("X", "M", "B", "E", "i", "I"):
            problems.append(f"event {i}: unknown phase {ph!r}")
        if ph == "X":
            ts, dur = e.get("ts"), e.get("dur")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"event {i}: bad ts {ts!r}")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i}: bad dur {dur!r}")
            if isinstance(ts, (int, float)):
                if last_ts is not None and ts < last_ts:
                    problems.append(f"event {i}: ts {ts} < previous {last_ts} (unsorted)")
                last_ts = ts
    return problems
