"""On-device replay buffers — first-party flashbax equivalents.

The reference uses flashbax (`fbx.make_item_buffer` ff_dqn.py:339-345,
`fbx.make_trajectory_buffer` ff_az.py:497, `fbx.make_prioritised_trajectory_buffer`
ff_rainbow.py:433 / rec_r2d2.py:644). These buffers are pure-functional pytrees
of preallocated arrays, so `add`/`sample` live INSIDE the compiled update step
(reference ff_dqn.py:142,185) and shard cleanly along the mesh data axis: each
shard owns an independent slice of the buffer, exactly like the reference's
per-device buffer sharding (ff_dqn.py:325-338).

TPU notes: all ops are scatter/gather with static shapes. Prioritized sampling
uses an O(N) cumulative-sum inverse-CDF rather than a host-side sum-tree — a
single fused scan+searchsorted is far faster on TPU than pointer chasing, and
it keeps sampling inside the jitted learner.
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array


class EmptyBufferSampleError(RuntimeError):
    """Sampling a buffer that cannot sample yet (docs/DESIGN.md §2.10).

    The buffers silently return ZERO-initialized items/sequences when
    nothing sampleable has been written (the documented foot-gun
    off_policy_core.require_first_add_samplable guards statically for the
    warmup-less AZ/MZ family) — this error makes the dynamic case loud
    under the opt-in debug guard."""


_SAMPLE_GUARD = os.environ.get("STOIX_TPU_BUFFER_DEBUG", "") not in ("", "0")


def set_sample_guard(enabled: bool) -> bool:
    """Toggle the debug sample guard (also armed by STOIX_TPU_BUFFER_DEBUG=1).

    The flag is read at TRACE time: programs compiled while it is on carry
    the check (an eager sample raises EmptyBufferSampleError directly; a
    traced sample raises through jax.debug.callback at run time, surfacing
    as an XlaRuntimeError whose message names EmptyBufferSampleError).
    Returns the previous value so tests can restore it."""
    global _SAMPLE_GUARD
    previous = _SAMPLE_GUARD
    _SAMPLE_GUARD = bool(enabled)
    return previous


def _raise_empty(ok: Any, what: str) -> None:
    if not bool(ok):
        raise EmptyBufferSampleError(
            f"EmptyBufferSampleError: sample() on an unfilled {what} — "
            "can_sample() is False, the returned batch would be "
            "zero-initialized garbage (guard armed by "
            "STOIX_TPU_BUFFER_DEBUG / buffers.set_sample_guard)"
        )


def _guard_sample(ok: Array, what: str) -> None:
    """Debug-only can_sample enforcement; a literal no-op unless armed."""
    if not _SAMPLE_GUARD:
        return
    if isinstance(ok, jax.core.Tracer):
        # In-jit path: the callback runs when the compiled program does;
        # its raise aborts execution with the typed message. Host transfer
        # is the point here — opt-in debug instrumentation only.
        jax.debug.callback(_raise_empty, ok, what)  # noqa: STX006 — opt-in debug guard
    else:
        _raise_empty(ok, what)


class ItemBufferState(NamedTuple):
    experience: Any  # pytree, leaves [capacity, ...]
    insert_pos: Array  # int32 — next write slot
    num_added: Array  # int32 — total items ever added


class ItemBufferSample(NamedTuple):
    experience: Any  # pytree, leaves [batch, ...]


class ItemBuffer(NamedTuple):
    """Uniform flat-transition buffer (fbx.make_item_buffer equivalent)."""

    init: Callable[[Any], ItemBufferState]
    add: Callable[[ItemBufferState, Any], ItemBufferState]
    sample: Callable[[ItemBufferState, Array], ItemBufferSample]
    can_sample: Callable[[ItemBufferState], Array]


def make_item_buffer(
    max_length: int, min_length: int, sample_batch_size: int, add_batch_size: int
) -> ItemBuffer:
    """Items are added in batches of `add_batch_size` (one per env per step)."""

    def init(item: Any) -> ItemBufferState:
        experience = jax.tree.map(
            lambda x: jnp.zeros((max_length,) + jnp.shape(x), jnp.asarray(x).dtype), item
        )
        return ItemBufferState(
            experience=experience,
            insert_pos=jnp.zeros((), jnp.int32),
            num_added=jnp.zeros((), jnp.int32),
        )

    def add(state: ItemBufferState, batch: Any) -> ItemBufferState:
        # Batch size is read from the input (static under trace), so warmup and
        # training can add different-sized batches through one buffer.
        n = jax.tree.leaves(batch)[0].shape[0]
        idx = (state.insert_pos + jnp.arange(n)) % max_length
        experience = jax.tree.map(
            lambda buf, new: buf.at[idx].set(new), state.experience, batch
        )
        return ItemBufferState(
            experience=experience,
            insert_pos=(state.insert_pos + n) % max_length,
            num_added=state.num_added + n,
        )

    def sample(state: ItemBufferState, key: Array) -> ItemBufferSample:
        _guard_sample(can_sample(state), "item buffer")
        current_size = jnp.minimum(state.num_added, max_length)
        idx = jax.random.randint(key, (sample_batch_size,), 0, jnp.maximum(current_size, 1))
        return ItemBufferSample(
            experience=jax.tree.map(lambda buf: buf[idx], state.experience)
        )

    def can_sample(state: ItemBufferState) -> Array:
        return state.num_added >= min_length

    return ItemBuffer(init, add, sample, can_sample)


class TrajectoryBufferState(NamedTuple):
    experience: Any  # pytree, leaves [add_batch(envs), time_capacity, ...]
    insert_pos: Array  # int32 — next time slot (shared across rows)
    num_added: Array  # int32 — total time steps ever written per row


class TrajectoryBufferSample(NamedTuple):
    experience: Any  # pytree, leaves [batch, sample_sequence_length, ...]


class TrajectoryBuffer(NamedTuple):
    init: Callable[[Any], TrajectoryBufferState]
    add: Callable[[TrajectoryBufferState, Any], TrajectoryBufferState]
    sample: Callable[[TrajectoryBufferState, Array], TrajectoryBufferSample]
    can_sample: Callable[[TrajectoryBufferState], Array]


def _trajectory_init(item: Any, add_batch_size: int, time_capacity: int) -> TrajectoryBufferState:
    experience = jax.tree.map(
        lambda x: jnp.zeros(
            (add_batch_size, time_capacity) + jnp.shape(x), jnp.asarray(x).dtype
        ),
        item,
    )
    return TrajectoryBufferState(
        experience=experience,
        insert_pos=jnp.zeros((), jnp.int32),
        num_added=jnp.zeros((), jnp.int32),
    )


def _trajectory_add(
    state: TrajectoryBufferState, batch: Any, time_capacity: int
) -> TrajectoryBufferState:
    """batch leaves: [add_batch, t_chunk, ...] written at insert_pos with wrap."""
    t_chunk = jax.tree.leaves(batch)[0].shape[1]
    idx = (state.insert_pos + jnp.arange(t_chunk)) % time_capacity
    experience = jax.tree.map(
        lambda buf, new: buf.at[:, idx].set(new), state.experience, batch
    )
    return TrajectoryBufferState(
        experience=experience,
        insert_pos=(state.insert_pos + t_chunk) % time_capacity,
        num_added=state.num_added + t_chunk,
    )


def _valid_starts(
    state: TrajectoryBufferState, time_capacity: int, seq_len: int
) -> tuple[Array, Array]:
    """Number of valid sequence start slots and the oldest valid slot.

    Sequences must not cross the write head once the buffer has wrapped
    (those time steps are not contiguous in experience time).
    """
    filled = jnp.minimum(state.num_added, time_capacity)
    # Max start count: filled - seq_len + 1, but when full, starts that would
    # cross insert_pos are invalid, leaving time_capacity - seq_len valid.
    not_wrapped = state.num_added <= time_capacity
    n_starts = jnp.where(
        not_wrapped,
        jnp.maximum(filled - seq_len + 1, 0),
        time_capacity - seq_len,
    )
    oldest = jnp.where(not_wrapped, 0, state.insert_pos)
    return n_starts, oldest


def make_trajectory_buffer(
    add_batch_size: int,
    sample_batch_size: int,
    sample_sequence_length: int,
    period: int = 1,
    max_length_time_axis: int = 10_000,
    min_length_time_axis: int = 1,
) -> TrajectoryBuffer:
    """Time-contiguous sequence buffer (fbx.make_trajectory_buffer equivalent).

    `period` strides the candidate start positions (period == sequence length
    gives non-overlapping samples).
    """
    time_capacity = max_length_time_axis

    def init(item: Any) -> TrajectoryBufferState:
        return _trajectory_init(item, add_batch_size, time_capacity)

    def add(state: TrajectoryBufferState, batch: Any) -> TrajectoryBufferState:
        return _trajectory_add(state, batch, time_capacity)

    def sample(state: TrajectoryBufferState, key: Array) -> TrajectoryBufferSample:
        _guard_sample(can_sample(state), "trajectory buffer")
        row_key, start_key = jax.random.split(key)
        rows = jax.random.randint(row_key, (sample_batch_size,), 0, add_batch_size)
        n_starts, oldest = _valid_starts(state, time_capacity, sample_sequence_length)
        n_periods = jnp.maximum(n_starts // period, 1)
        start_periods = jax.random.randint(start_key, (sample_batch_size,), 0, n_periods)
        starts = (oldest + start_periods * period) % time_capacity
        t_idx = (starts[:, None] + jnp.arange(sample_sequence_length)[None, :]) % time_capacity

        experience = jax.tree.map(lambda buf: buf[rows[:, None], t_idx], state.experience)
        return TrajectoryBufferSample(experience=experience)

    def can_sample(state: TrajectoryBufferState) -> Array:
        return state.num_added >= jnp.maximum(min_length_time_axis, sample_sequence_length)

    return TrajectoryBuffer(init, add, sample, can_sample)


class PrioritisedTrajectoryBufferState(NamedTuple):
    experience: Any  # [add_batch, time_capacity, ...]
    priorities: Array  # [add_batch, num_slots] — per sequence-start slot
    insert_pos: Array
    num_added: Array


class PrioritisedSample(NamedTuple):
    experience: Any  # [batch, seq_len, ...]
    indices: Array  # [batch, 2] — (row, slot) for set_priorities
    probabilities: Array  # [batch]


class PrioritisedTrajectoryBuffer(NamedTuple):
    init: Callable[[Any], PrioritisedTrajectoryBufferState]
    add: Callable[[PrioritisedTrajectoryBufferState, Any], PrioritisedTrajectoryBufferState]
    sample: Callable[[PrioritisedTrajectoryBufferState, Array], PrioritisedSample]
    set_priorities: Callable[
        [PrioritisedTrajectoryBufferState, Array, Array], PrioritisedTrajectoryBufferState
    ]
    can_sample: Callable[[PrioritisedTrajectoryBufferState], Array]


def make_prioritised_trajectory_buffer(
    add_batch_size: int,
    sample_batch_size: int,
    sample_sequence_length: int,
    period: int = 1,
    max_length_time_axis: int = 10_000,
    min_length_time_axis: int = 1,
    priority_exponent: float = 0.6,
) -> PrioritisedTrajectoryBuffer:
    """Prioritized sequence replay (Rainbow / R2D2). Priorities are kept per
    sequence-start SLOT (time_capacity // period slots per row); sampling is an
    inverse-CDF over the flattened priority table — one cumsum + searchsorted,
    fully on-device (replaces host sum-trees).
    """
    time_capacity = max_length_time_axis
    num_slots = time_capacity // period

    def init(item: Any) -> PrioritisedTrajectoryBufferState:
        base = _trajectory_init(item, add_batch_size, time_capacity)
        return PrioritisedTrajectoryBufferState(
            experience=base.experience,
            priorities=jnp.zeros((add_batch_size, num_slots), jnp.float32),
            insert_pos=base.insert_pos,
            num_added=base.num_added,
        )

    def add(state: PrioritisedTrajectoryBufferState, batch: Any) -> PrioritisedTrajectoryBufferState:
        t_chunk = jax.tree.leaves(batch)[0].shape[1]
        base = TrajectoryBufferState(state.experience, state.insert_pos, state.num_added)
        new_base = _trajectory_add(base, batch, time_capacity)

        # New data gets max priority so it is sampled at least once. Slots whose
        # sequences would now cross the write head are invalidated implicitly by
        # _valid_starts at sample time; here we set newly-writable slots.
        max_prio = jnp.maximum(jnp.max(state.priorities), 1.0)
        first_slot = state.insert_pos // period
        n_new_slots = (t_chunk + period - 1) // period
        slot_idx = (first_slot + jnp.arange(num_slots)) % num_slots
        write_mask = jnp.arange(num_slots) < n_new_slots
        updates = jnp.where(write_mask[None, :], max_prio, state.priorities[:, slot_idx])
        priorities = state.priorities.at[:, slot_idx].set(updates)

        return PrioritisedTrajectoryBufferState(
            experience=new_base.experience,
            priorities=priorities,
            insert_pos=new_base.insert_pos,
            num_added=new_base.num_added,
        )

    def sample(state: PrioritisedTrajectoryBufferState, key: Array) -> PrioritisedSample:
        _guard_sample(can_sample(state), "prioritised trajectory buffer")
        n_starts, oldest = _valid_starts(
            TrajectoryBufferState(state.experience, state.insert_pos, state.num_added),
            time_capacity,
            sample_sequence_length,
        )
        # Everything below stays in PHYSICAL slot space so priorities, sampled
        # data, and returned indices all refer to the same slots (mixing
        # ordered/physical indexing desynchronizes PER after wraparound).
        slot_starts = jnp.arange(num_slots) * period  # absolute time index per slot
        offset_from_oldest = (slot_starts - oldest) % time_capacity
        valid = offset_from_oldest < n_starts

        flat_prio = jnp.where(valid[None, :], state.priorities, 0.0).reshape(-1)
        total = jnp.sum(flat_prio)
        cdf = jnp.cumsum(flat_prio)
        u = jax.random.uniform(key, (sample_batch_size,)) * total
        flat_idx = jnp.searchsorted(cdf, u, side="right")
        flat_idx = jnp.clip(flat_idx, 0, add_batch_size * num_slots - 1)
        rows = flat_idx // num_slots
        slots = flat_idx % num_slots
        starts = slot_starts[slots]
        t_idx = (starts[:, None] + jnp.arange(sample_sequence_length)[None, :]) % time_capacity

        experience = jax.tree.map(lambda buf: buf[rows[:, None], t_idx], state.experience)
        probs = flat_prio[flat_idx] / jnp.maximum(total, 1e-9)
        indices = jnp.stack([rows, slots], axis=-1)
        return PrioritisedSample(experience=experience, indices=indices, probabilities=probs)

    def set_priorities(
        state: PrioritisedTrajectoryBufferState, indices: Array, priorities: Array
    ) -> PrioritisedTrajectoryBufferState:
        rows, slots = indices[:, 0], indices[:, 1]
        new = state.priorities.at[rows, slots].set(
            jnp.power(jnp.abs(priorities) + 1e-6, priority_exponent)
        )
        return state._replace(priorities=new)

    def can_sample(state: PrioritisedTrajectoryBufferState) -> Array:
        return state.num_added >= jnp.maximum(min_length_time_axis, sample_sequence_length)

    return PrioritisedTrajectoryBuffer(init, add, sample, set_priorities, can_sample)


def make_flat_buffer(
    max_length: int, min_length: int, sample_batch_size: int, add_batch_size: int
) -> ItemBuffer:
    """Alias matching flashbax's flat-buffer naming."""
    return make_item_buffer(max_length, min_length, sample_batch_size, add_batch_size)
