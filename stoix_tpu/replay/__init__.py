"""Device-resident sharded replay service (docs/DESIGN.md §2.10).

Buffer state sharded across learner HBM; prioritized sampling executed where
the data lives so only sampled minibatches — never raw experience — cross
the interconnect. `replay.core` is the per-shard functional layer (embeddable
in any shard_map over the data axis), `replay.service` the host-facing jitted
program set used by the Sebulba off-policy ingestion path.
"""

from stoix_tpu.replay.core import (  # noqa: F401 — public API
    ShardedReplayCore,
    ShardedReplayState,
    ShardedSample,
    make_reference_replay,
    make_sharded_replay,
    replicated_key,
)
from stoix_tpu.replay.service import (  # noqa: F401
    ShardedReplayService,
    service_from_config,
    tree_bytes,
)
