"""ItemBuffer-compatible facade over the sharded replay core.

`systems/off_policy_core.py` (the DQN/SAC/DDPG family) talks to replay
through the four-function ItemBuffer interface; this wrapper lets the SAME
per-shard learner run against the cross-shard sampler with no interface
change — only the sampling semantics move from per-shard-uniform to the
GLOBAL draw of replay/core.py (`system.replay.impl = sharded`).

The learner's per-shard keys differ across shards by construction (they
drive env stepping); the global draw needs one key per update-batch replica
identical across shards, so `sample` first replicates the incoming key over
the axis (shard 0 wins — an all_gather of 8 bytes).
"""

from __future__ import annotations

from stoix_tpu.buffers.buffers import ItemBuffer, ItemBufferSample
from stoix_tpu.replay.core import make_sharded_replay, replicated_key


def make_sharded_item_buffer(
    capacity_per_shard: int,
    sample_batch_size: int,
    num_shards: int,
    min_fill: int,
    axis: str = "data",
) -> ItemBuffer:
    """`sample_batch_size` is GLOBAL; each shard receives its
    sample_batch_size // num_shards slice — sized so the per-shard batch
    matches the local impl's, only its content is drawn fleet-wide.

    Always uniform: the four-function ItemBuffer interface has no
    set_priorities seam, so a prioritized table could never be updated
    (off_policy_core refuses replay.prioritized on this path; Sebulba
    ff_dqn is the prioritized consumer, driving the core directly)."""
    core = make_sharded_replay(
        capacity=capacity_per_shard,
        sample_batch_size=sample_batch_size,
        num_shards=num_shards,
        axis=axis,
        prioritized=False,
        min_fill=min_fill,
    )

    def sample(state, key):
        drawn = core.sample(state, replicated_key(key, axis))
        return ItemBufferSample(experience=drawn.experience)

    return ItemBuffer(core.init, core.add, sample, core.can_sample)
