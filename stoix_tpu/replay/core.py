"""Per-shard functional core of the device-resident replay service.

The buffer state never leaves learner HBM: each data shard owns an
independent ring of `capacity` items plus a per-slot priority table, and
every op is written PER SHARD so it can run inside any `shard_map` over the
data axis — embedded in an Anakin learner (off_policy_core) or wrapped as a
standalone jitted program (replay/service.py, the Sebulba path).

Sampling where the data lives (docs/DESIGN.md §2.10; the thesis of
"In-Network Experience Sampling", arxiv 2110.13506): a draw of the GLOBAL
batch costs

  1. one `all_gather` of the K scalar shard masses — the cross-shard
     normalization. Every shard computes the same total mass and the same
     exclusive-prefix ownership bounds, so the global inverse-CDF partitions
     the unit interval across shards exactly (shard k owns u in
     [bounds[k-1], bounds[k]) and the last shard additionally absorbs the
     floating-point top edge).
  2. one local prefix-sum + searchsorted per shard (the TPU-friendly
     sum-tree equivalent: a fused cumsum+searchsorted beats pointer chasing
     on the VPU and stays inside the compiled program, see buffers.py).
  3. one `psum` of the OWNER-MASKED sampled rows — each drawn row is owned
     by exactly one shard, every other shard contributes zeros, so the sum
     reconstructs the batch on every shard and only the sampled minibatch
     (plus its indices and probabilities) ever crosses the interconnect.
     Raw experience never moves.

On a single-shard mesh every collective degenerates to the identity, so the
sharded sampler is BITWISE equal to the single-device reference below
(tests/test_replay.py pins it).

Determinism contract: `local_sample` must be called with a key REPLICATED
across the axis (every shard draws the same uniforms — that is what makes
ownership a partition). `replicated_key` converts a per-shard key.

Axis names are parameters, never literals, so this module stays axis-generic
(and STX007-clean by the variable-axis rule).
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp

Array = jax.Array


class ShardedReplayState(NamedTuple):
    """One shard's view (leaves carry NO shard axis; shard_map adds it)."""

    experience: Any  # pytree, leaves [capacity, ...]
    priorities: Array  # [capacity] f32 — 0.0 marks an unwritten slot
    insert_pos: Array  # int32 — next write slot in this shard's ring
    num_added: Array  # int32 — items ever written to THIS shard


class ShardedSample(NamedTuple):
    """This shard's slice of one globally-drawn batch."""

    experience: Any  # pytree, leaves [batch_per_shard, ...]
    indices: Array  # [batch_per_shard] int32 global flat (shard * capacity + slot)
    probabilities: Array  # [batch_per_shard] f32 — p_i under the GLOBAL draw


class ShardedReplayCore(NamedTuple):
    """Per-shard ops, all safe inside a shard_map over `axis`."""

    init: Callable[[Any], ShardedReplayState]
    add: Callable[[ShardedReplayState, Any], ShardedReplayState]
    sample: Callable[[ShardedReplayState, Array], ShardedSample]
    set_priorities: Callable[[ShardedReplayState, Array, Array], ShardedReplayState]
    can_sample: Callable[[ShardedReplayState], Array]
    occupancy: Callable[[ShardedReplayState], Array]


def replicated_key(key: Array, axis: str) -> Array:
    """Make a per-shard key identical on every shard (shard 0's key wins).
    Identity on a 1-shard axis, so bitwise equivalence with the reference
    sampler is preserved."""
    return jax.lax.all_gather(key, axis)[0]


def _where_rows(mask: Array, rows: Array) -> Array:
    """Zero out non-owned rows (any dtype; bools pass through jnp.where)."""
    expanded = mask.reshape(mask.shape + (1,) * (rows.ndim - 1))
    return jnp.where(expanded, rows, jnp.zeros_like(rows))


def make_sharded_replay(
    capacity: int,
    sample_batch_size: int,
    num_shards: int,
    axis: str = "data",
    prioritized: bool = False,
    priority_exponent: float = 0.6,
    min_fill: int = 1,
) -> ShardedReplayCore:
    """Build the per-shard op set.

    `capacity` and `sample_batch_size` are PER-SHARD and GLOBAL respectively:
    each shard rings `capacity` items, one `sample` call draws
    `sample_batch_size` items from the global priority distribution and
    hands each shard its `sample_batch_size // num_shards` slice.
    """
    if sample_batch_size % num_shards != 0:
        raise ValueError(
            f"sample_batch_size ({sample_batch_size}) must divide evenly over "
            f"{num_shards} shard(s) — every shard consumes an equal slice"
        )
    batch_per_shard = sample_batch_size // num_shards

    def init(item: Any) -> ShardedReplayState:
        experience = jax.tree.map(
            lambda x: jnp.zeros((capacity,) + jnp.shape(x), jnp.asarray(x).dtype), item
        )
        return ShardedReplayState(
            experience=experience,
            priorities=jnp.zeros((capacity,), jnp.float32),
            insert_pos=jnp.zeros((), jnp.int32),
            num_added=jnp.zeros((), jnp.int32),
        )

    def add(state: ShardedReplayState, batch: Any) -> ShardedReplayState:
        n = jax.tree.leaves(batch)[0].shape[0]
        idx = (state.insert_pos + jnp.arange(n)) % capacity
        experience = jax.tree.map(
            lambda buf, new: buf.at[idx].set(new), state.experience, batch
        )
        if prioritized:
            # New data samples at least once: written slots take the GLOBAL
            # max priority (pmax degenerates to the local max on one shard,
            # matching the single-device reference bitwise).
            new_prio = jnp.maximum(
                jax.lax.pmax(jnp.max(state.priorities), axis), 1.0
            )
        else:
            # Uniform mode: every written slot weighs 1.0, so the global
            # inverse-CDF is uniform over all FILLED slots fleet-wide even
            # when shards fill unevenly (Sebulba actors are not lockstep).
            new_prio = jnp.float32(1.0)
        priorities = state.priorities.at[idx].set(new_prio)
        return ShardedReplayState(
            experience=experience,
            priorities=priorities,
            insert_pos=(state.insert_pos + n) % capacity,
            num_added=state.num_added + n,
        )

    def sample(state: ShardedReplayState, key: Array) -> ShardedSample:
        # Cross-shard normalization: ONE all_gather of the K scalar masses.
        mass = jnp.sum(state.priorities)
        masses = jax.lax.all_gather(mass, axis)  # [K], identical on all shards
        total = jnp.sum(masses)
        bounds = jnp.cumsum(masses)  # inclusive prefix, identical everywhere
        k = jax.lax.axis_index(axis)
        lower = jnp.where(k == 0, 0.0, bounds[jnp.maximum(k - 1, 0)])

        # Same key on every shard => same uniforms => ownership partitions.
        u = jax.random.uniform(key, (sample_batch_size,)) * total
        owned = (u >= lower) & ((u < bounds[k]) | (k == num_shards - 1))
        pos = u - lower
        cdf = jnp.cumsum(state.priorities)
        # Clip into the WRITTEN prefix of the ring, not just [0, capacity):
        # f32 rounding slivers in the ownership bounds can push `pos` past
        # this shard's own mass, where searchsorted lands one past the last
        # written slot — an unwritten zero row with probability 0. The
        # reference sampler applies the identical clip (bitwise pin).
        filled = jnp.minimum(state.num_added, capacity)
        idx = jnp.clip(
            jnp.searchsorted(cdf, pos, side="right"), 0, jnp.maximum(filled - 1, 0)
        )

        rows = jax.tree.map(
            lambda buf: _where_rows(owned, buf[idx]), state.experience
        )
        probs = jnp.where(owned, state.priorities[idx] / jnp.maximum(total, 1e-9), 0.0)
        g_idx = jnp.where(owned, k.astype(jnp.int32) * capacity + idx, 0)

        # The only payload that crosses the interconnect: the sampled batch.
        rows, probs, g_idx = jax.lax.psum((rows, probs, g_idx), axis)

        start = k * batch_per_shard
        slice_rows = lambda x: jax.lax.dynamic_slice_in_dim(x, start, batch_per_shard)
        return ShardedSample(
            experience=jax.tree.map(slice_rows, rows),
            indices=slice_rows(g_idx),
            probabilities=slice_rows(probs),
        )

    def set_priorities(
        state: ShardedReplayState, indices: Array, priorities: Array
    ) -> ShardedReplayState:
        # Each shard holds its slice of the batch's (index, priority) pairs —
        # gather the full set (the indices/weights half of the interconnect
        # cost) and scatter only the slots this shard owns.
        all_idx = jax.lax.all_gather(indices, axis).reshape(-1)
        all_p = jax.lax.all_gather(priorities, axis).reshape(-1)
        k = jax.lax.axis_index(axis)
        mine = (all_idx // capacity) == k
        # Non-owned updates point one past the end and mode="drop"s away.
        slot = jnp.where(mine, all_idx % capacity, capacity)
        new = jnp.power(jnp.abs(all_p) + 1e-6, priority_exponent)
        updated = state.priorities.at[slot].set(new, mode="drop")
        return state._replace(priorities=updated)

    def can_sample(state: ShardedReplayState) -> Array:
        filled = jnp.minimum(state.num_added, capacity)
        return jax.lax.psum(filled, axis) >= min_fill

    def occupancy(state: ShardedReplayState) -> Array:
        return jnp.minimum(state.num_added, capacity)

    return ShardedReplayCore(init, add, sample, set_priorities, can_sample, occupancy)


def make_reference_replay(
    capacity: int,
    sample_batch_size: int,
    prioritized: bool = False,
    priority_exponent: float = 0.6,
    min_fill: int = 1,
) -> ShardedReplayCore:
    """The single-device reference sampler: the same math with every
    collective removed. `make_sharded_replay` on a 1-shard mesh must match it
    BITWISE (tests/test_replay.py) — this is the equivalence oracle, not a
    production path (production single-shard runs use the sharded core on a
    trivial mesh, one code path for every topology)."""

    def init(item: Any) -> ShardedReplayState:
        experience = jax.tree.map(
            lambda x: jnp.zeros((capacity,) + jnp.shape(x), jnp.asarray(x).dtype), item
        )
        return ShardedReplayState(
            experience=experience,
            priorities=jnp.zeros((capacity,), jnp.float32),
            insert_pos=jnp.zeros((), jnp.int32),
            num_added=jnp.zeros((), jnp.int32),
        )

    def add(state: ShardedReplayState, batch: Any) -> ShardedReplayState:
        n = jax.tree.leaves(batch)[0].shape[0]
        idx = (state.insert_pos + jnp.arange(n)) % capacity
        experience = jax.tree.map(
            lambda buf, new: buf.at[idx].set(new), state.experience, batch
        )
        if prioritized:
            new_prio = jnp.maximum(jnp.max(state.priorities), 1.0)
        else:
            new_prio = jnp.float32(1.0)
        return ShardedReplayState(
            experience=experience,
            priorities=state.priorities.at[idx].set(new_prio),
            insert_pos=(state.insert_pos + n) % capacity,
            num_added=state.num_added + n,
        )

    def sample(state: ShardedReplayState, key: Array) -> ShardedSample:
        mass = jnp.sum(state.priorities)
        masses = mass[None]
        total = jnp.sum(masses)
        u = jax.random.uniform(key, (sample_batch_size,)) * total
        cdf = jnp.cumsum(state.priorities)
        filled = jnp.minimum(state.num_added, capacity)
        idx = jnp.clip(
            jnp.searchsorted(cdf, u, side="right"), 0, jnp.maximum(filled - 1, 0)
        )
        rows = jax.tree.map(lambda buf: _where_rows(jnp.ones_like(u, bool), buf[idx]),
                            state.experience)
        probs = state.priorities[idx] / jnp.maximum(total, 1e-9)
        return ShardedSample(experience=rows, indices=idx, probabilities=probs)

    def set_priorities(
        state: ShardedReplayState, indices: Array, priorities: Array
    ) -> ShardedReplayState:
        new = jnp.power(jnp.abs(priorities) + 1e-6, priority_exponent)
        return state._replace(priorities=state.priorities.at[indices].set(new))

    def can_sample(state: ShardedReplayState) -> Array:
        return jnp.minimum(state.num_added, capacity) >= min_fill

    def occupancy(state: ShardedReplayState) -> Array:
        return jnp.minimum(state.num_added, capacity)

    return ShardedReplayCore(init, add, sample, set_priorities, can_sample, occupancy)
