"""Host-facing device-resident replay service (docs/DESIGN.md §2.10).

`ShardedReplayService` owns a buffer whose state is a sharded pytree living
in learner-device HBM: every leaf carries a leading [num_shards] axis with
spec P(axis), so shard k's ring and priority table live ONLY in device k's
memory. Each op is ONE jitted shard_map program, built once at construction
(STX012: never per call):

  add(batch)          batch is a GLOBAL array sharded P(axis) on its item
                      axis — assembled upstream via
                      parallel.assemble_global_array from per-device shards,
                      so raw experience lands on its owning shard with no
                      host concat and no cross-device copy.
  sample(key)         the global prioritized/uniform draw of replay/core.py;
                      returns a ShardedSample of GLOBAL arrays sharded
                      P(axis) — each learner shard already holds its slice.
  set_priorities(...) scatter new priorities through global flat indices
                      (cross-shard: each shard gathers the full index set
                      and keeps what it owns).
  can_sample()        psum'd global fill >= min_fill, as a host bool.

The service also meters itself into the PR 2 registry
(`stoix_tpu_replay_*`): add/sample op+item counters, ingested-bytes vs
sampled-bytes-crossed counters (byte sizes are static properties of the
avals — zero device syncs on the hot path), and occupancy / per-shard
priority-mass gauges refreshed by the off-hot-path `observe()`.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stoix_tpu.observability import get_registry
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.replay.core import ShardedSample, make_sharded_replay


def tree_bytes(tree: Any) -> int:
    """Static byte size of a pytree of arrays (shape x itemsize; no fetch)."""
    return int(
        sum(int(np.prod(x.shape)) * x.dtype.itemsize for x in jax.tree.leaves(tree))
    )


def _squeeze(tree: Any) -> Any:
    return jax.tree.map(lambda x: x[0], tree)


def _unsqueeze(tree: Any) -> Any:
    return jax.tree.map(lambda x: x[None], tree)


class ShardedReplayService:
    """Device-resident sharded replay over a mesh axis.

    `item` is one example transition (no batch axis) defining leaf shapes
    and dtypes; `capacity_per_shard` rings per shard; `sample_batch_size`
    is the GLOBAL batch drawn per sample call.
    """

    def __init__(
        self,
        mesh: Mesh,
        item: Any,
        *,
        capacity_per_shard: int,
        sample_batch_size: int,
        axis: str = "data",
        prioritized: bool = False,
        priority_exponent: float = 0.6,
        min_fill: int = 1,
    ):
        self.mesh = mesh
        self.axis = axis
        self.num_shards = int(mesh.shape[axis])
        self.capacity_per_shard = int(capacity_per_shard)
        self.sample_batch_size = int(sample_batch_size)
        self.prioritized = bool(prioritized)
        self.core = make_sharded_replay(
            capacity=self.capacity_per_shard,
            sample_batch_size=self.sample_batch_size,
            num_shards=self.num_shards,
            axis=axis,
            prioritized=self.prioritized,
            priority_exponent=priority_exponent,
            min_fill=min_fill,
        )

        sharded = NamedSharding(mesh, P(axis))
        host_state = self.core.init(item)
        self._state = jax.device_put(
            jax.tree.map(
                lambda x: jnp.broadcast_to(x, (self.num_shards,) + x.shape), host_state
            ),
            sharded,
        )

        core = self.core

        def per_shard_add(state, batch):
            return _unsqueeze(core.add(_squeeze(state), batch))

        def per_shard_sample(state, key):
            return core.sample(_squeeze(state), key)

        def per_shard_set_priorities(state, indices, priorities):
            return _unsqueeze(
                core.set_priorities(_squeeze(state), indices, priorities)
            )

        def per_shard_can_sample(state):
            return core.can_sample(_squeeze(state))

        def per_shard_stats(state):
            s = _squeeze(state)
            return core.occupancy(s)[None], jnp.sum(s.priorities)[None]

        # ONE jitted program per op, built here and reused for the service's
        # lifetime. The add donates the old state buffers — the ring is the
        # largest live allocation on a learner device, and the service owns
        # it exclusively (the previous state is never read again).
        self._add = jax.jit(
            shard_map(
                per_shard_add, mesh=mesh, in_specs=(P(axis), P(axis)),
                out_specs=P(axis),
            ),
            donate_argnums=(0,),
        )
        self._sample = jax.jit(
            shard_map(
                per_shard_sample, mesh=mesh, in_specs=(P(axis), P()),
                out_specs=P(axis),
            )
        )
        self._set_priorities = jax.jit(
            shard_map(
                per_shard_set_priorities, mesh=mesh,
                in_specs=(P(axis), P(axis), P(axis)), out_specs=P(axis),
            ),
            donate_argnums=(0,),
        )
        self._can_sample = jax.jit(
            shard_map(
                per_shard_can_sample, mesh=mesh, in_specs=(P(axis),),
                out_specs=P(),
            )
        )
        self._stats = jax.jit(
            shard_map(
                per_shard_stats, mesh=mesh, in_specs=(P(axis),),
                out_specs=(P(axis), P(axis)),
            )
        )

        registry = get_registry()
        self._add_ops = registry.counter(
            "stoix_tpu_replay_add_ops_total", "Replay add programs executed"
        )
        self._add_items = registry.counter(
            "stoix_tpu_replay_add_items_total", "Transitions ingested into replay"
        )
        self._ingested_bytes = registry.counter(
            "stoix_tpu_replay_ingested_bytes_total",
            "Raw experience bytes ingested (these bytes never cross shards)",
        )
        self._sample_ops = registry.counter(
            "stoix_tpu_replay_sample_ops_total", "Replay sample programs executed"
        )
        self._sample_items = registry.counter(
            "stoix_tpu_replay_sample_items_total", "Transitions drawn from replay"
        )
        self._sampled_bytes = registry.counter(
            "stoix_tpu_replay_sampled_bytes_crossed_total",
            "Logical bytes of sampled minibatches (+ indices/probabilities) "
            "reconstructed across shards by the sample psum",
        )
        self._occupancy_gauge = registry.gauge(
            "stoix_tpu_replay_occupancy", "Items currently held, per shard"
        )
        self._mass_gauge = registry.gauge(
            "stoix_tpu_replay_priority_mass", "Total sampling mass, per shard"
        )

    # -- state ownership -----------------------------------------------------
    @property
    def state(self) -> Any:
        """The live sharded buffer state. Systems embedding replay ops in
        their own learn program (Sebulba ff_dqn) read this, thread it through
        the program, and hand the result back via `commit`."""
        return self._state

    def commit(self, new_state: Any) -> None:
        self._state = new_state

    # -- ops -----------------------------------------------------------------
    def add(self, global_batch: Any) -> None:
        """Ingest a GLOBAL batch sharded P(axis) on its leading item axis."""
        n = jax.tree.leaves(global_batch)[0].shape[0]
        self._state = self._add(self._state, global_batch)
        self._add_ops.inc()
        self._add_items.inc(n)
        self._ingested_bytes.inc(tree_bytes(global_batch))

    def sample(self, key: jax.Array) -> ShardedSample:
        out = self._sample(self._state, key)
        self._sample_ops.inc()
        self._sample_items.inc(self.sample_batch_size)
        self._sampled_bytes.inc(self.sample_bytes_crossed)
        return out

    def note_embedded_samples(self, ops: int = 1) -> None:
        """Account sample draws made by an EMBEDDED `core.sample` inside a
        system's own learn program (Sebulba ff_dqn fuses sample+update into
        one shard_map, bypassing the service's jitted sample op — the
        transport accounting must still see those draws)."""
        self._sample_ops.inc(ops)
        self._sample_items.inc(ops * self.sample_batch_size)
        self._sampled_bytes.inc(ops * self.sample_bytes_crossed)

    def set_priorities(self, indices: jax.Array, priorities: jax.Array) -> None:
        self._state = self._set_priorities(self._state, indices, priorities)

    def can_sample(self) -> bool:
        return bool(np.asarray(self._can_sample(self._state)))

    # -- accounting ----------------------------------------------------------
    @property
    def sample_bytes_crossed(self) -> int:
        """Logical interconnect payload of ONE sample op: the global batch's
        rows plus indices (int32) and probabilities (f32). The psum's ring
        schedule moves ~2(K-1)/K x this; the counter tracks the logical
        payload so the number is topology-independent."""
        row_bytes = sum(
            int(np.prod(x.shape[2:])) * x.dtype.itemsize
            for x in jax.tree.leaves(self._state.experience)
        )
        return self.sample_batch_size * (int(row_bytes) + 8)

    def observe(self) -> dict:
        """Off-hot-path telemetry refresh: fetch the [K] occupancy and
        priority-mass vectors (tiny) and publish per-shard gauges."""
        occupancy, mass = jax.tree.map(np.asarray, self._stats(self._state))
        for shard in range(self.num_shards):
            labels = {"shard": str(shard)}
            self._occupancy_gauge.set(float(occupancy[shard]), labels)
            self._mass_gauge.set(float(mass[shard]), labels)
        return {
            "occupancy": occupancy.tolist(),
            "priority_mass": [float(m) for m in mass],
        }

    def stats(self) -> dict:
        """Cumulative transport accounting (bench.py --replay reads this)."""
        return {
            "add_ops": int(self._add_ops.value()),
            "added_items": int(self._add_items.value()),
            "ingested_bytes_total": int(self._ingested_bytes.value()),
            "sample_ops": int(self._sample_ops.value()),
            "sampled_items": int(self._sample_items.value()),
            "sampled_bytes_crossed": int(self._sampled_bytes.value()),
        }


def service_from_config(
    mesh: Mesh, item: Any, config: Any, axis: str = "data"
) -> Optional["ShardedReplayService"]:
    """Build a service from `system.replay` + the global buffer/batch totals
    (None when replay.impl != sharded). Capacity and batch divide over the
    axis exactly like off_policy_core's per-shard sizing."""
    replay_cfg = dict(config.system.get("replay") or {})
    if str(replay_cfg.get("impl", "local")) != "sharded":
        return None
    n_shards = int(mesh.shape[axis])
    capacity = max(1, int(config.system.total_buffer_size) // n_shards)
    batch = int(config.system.total_batch_size)
    return ShardedReplayService(
        mesh,
        item,
        capacity_per_shard=capacity,
        sample_batch_size=batch,
        axis=axis,
        prioritized=bool(replay_cfg.get("prioritized", False)),
        priority_exponent=float(replay_cfg.get("priority_exponent", 0.6)),
        min_fill=max(1, int(replay_cfg.get("min_fill", batch))),
    )
