"""Optimizer schedules (reference stoix/utils/training.py:6-53)."""

from __future__ import annotations

from typing import Any, Callable, Union

import optax


def make_learning_rate(
    init_lr: float,
    config: Any,
    epochs: int = 1,
    num_minibatches: int = 1,
) -> Union[float, Callable[[int], float]]:
    """Constant LR, or linear decay to 0 over every optimizer step of the run
    when `system.decay_learning_rates` is set."""
    if not config.system.get("decay_learning_rates", False):
        return init_lr
    total_steps = int(config.arch.num_updates) * int(epochs) * int(num_minibatches)
    return optax.linear_schedule(init_lr, 0.0, max(1, total_steps))
