"""Budget arithmetic: reconcile total_timesteps <-> num_updates and derive
per-shard env counts (reference stoix/utils/total_timestep_checker.py:9-318).

Anakin accounting (per update):
    steps_per_update = rollout_length * total_num_envs
    num_updates      = total_timesteps // steps_per_update

`total_num_envs` is GLOBAL; each data shard runs
total_num_envs / (num_data_shards * update_batch_size) envs.
"""

from __future__ import annotations

from typing import Any

from stoix_tpu.observability import get_logger


def _log():
    # Resolved at the log site, not import time, so an application's
    # logging config (basicConfig/root handlers) wins (see get_logger).
    return get_logger("stoix_tpu.timestep_check")


def check_total_timesteps(config: Any, num_data_shards: int) -> Any:
    arch = config.arch
    system = config.system

    update_batch_size = int(arch.get("update_batch_size", 1))
    total_num_envs = int(arch.total_num_envs)
    divisor = num_data_shards * update_batch_size
    if total_num_envs % divisor != 0:
        raise ValueError(
            f"arch.total_num_envs ({total_num_envs}) must be divisible by "
            f"num_data_shards * update_batch_size ({num_data_shards} * {update_batch_size})"
        )
    arch.num_envs_per_shard = total_num_envs // divisor

    steps_per_update = int(system.rollout_length) * total_num_envs
    if arch.get("num_updates") in (None, "~"):
        assert arch.get("total_timesteps") is not None, (
            "Set either arch.total_timesteps or arch.num_updates"
        )
        arch.num_updates = max(1, int(float(arch.total_timesteps)) // steps_per_update)
    requested = arch.get("total_timesteps")
    arch.total_timesteps = int(arch.num_updates) * steps_per_update
    if requested is not None and int(float(requested)) != arch.total_timesteps:
        _log().info(
            f"[timestep-check] total_timesteps adjusted {int(float(requested))} -> "
            f"{arch.total_timesteps} (num_updates={arch.num_updates}, "
            f"steps/update={steps_per_update})"
        )

    num_evaluation = max(1, int(arch.get("num_evaluation", 1)))
    num_updates = int(arch.num_updates)
    if num_updates % num_evaluation != 0:
        if num_updates >= num_evaluation:
            # Keep the REQUESTED eval cadence and trim num_updates down to a
            # multiple of it (costs < one eval period of budget). The old
            # round-evals-down-to-a-divisor rule degenerated on awkward
            # update counts: e.g. 2929 updates (divisors 1/29/101/2929) at 20
            # requested evals collapsed to ONE eval — every update fused into
            # one compiled program (unobservable, and big enough to hit
            # device-runtime execution limits: the round-2 TPU wedge), which
            # is exactly what this check exists to prevent.
            trimmed = (num_updates // num_evaluation) * num_evaluation
            _log().info(
                f"[timestep-check] num_updates adjusted {num_updates} -> "
                f"{trimmed} (multiple of num_evaluation={num_evaluation}; "
                f"total_timesteps {arch.total_timesteps} -> "
                f"{trimmed * steps_per_update})"
            )
            num_updates = trimmed
            arch.num_updates = trimmed
            arch.total_timesteps = trimmed * steps_per_update
        else:
            requested_evals = num_evaluation
            num_evaluation = num_updates  # one eval per update
            _log().info(
                f"[timestep-check] num_evaluation adjusted {requested_evals} "
                f"-> {num_evaluation} (run has only {num_updates} updates)"
            )
    arch.num_evaluation = num_evaluation
    arch.num_updates_per_eval = int(arch.num_updates) // num_evaluation
    return config
