"""Orbax-backed checkpointing (reference stoix/utils/checkpointing.py:20-187).

Saves learner state keyed by timestep with best-by-episode-return tracking and
config-as-metadata with a major-version compatibility check. TPU-native
difference from the reference: states are GLOBAL (sharded) arrays — orbax
handles sharded save/restore natively, so there is no unreplicate step
(SURVEY.md §7.1.1).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

# 2.0: continuous MPO/V-MPO dual variables changed shape from (2,) to
# [2, action_dim] (per-dimension KL constraints) — old checkpoints cannot
# restore into the new template.
# 3.0: PPOLearnerState grew a `kl_beta` leaf (adaptive-KL PPO-penalty state)
# — pre-3.0 PPO/DPO/penalty checkpoints lack it and cannot restore into the
# new template.
CHECKPOINTER_VERSION = 3.0


class Checkpointer:
    def __init__(
        self,
        model_name: str,
        metadata: Optional[dict] = None,
        rel_dir: str = "checkpoints",
        checkpoint_uid: Optional[str] = None,
        save_interval_steps: int = 1,
        max_to_keep: Optional[int] = 1,
        keep_period: Optional[int] = None,
    ):
        import time

        uid = checkpoint_uid
        if uid is None:
            uid = time.strftime("%Y%m%d%H%M%S")
            if jax.process_count() > 1:
                # All processes must agree on the directory (collective save);
                # startup skew can cross a second boundary, so broadcast the
                # coordinator's stamp.
                import numpy as np
                from jax.experimental import multihost_utils

                stamp = multihost_utils.broadcast_one_to_all(
                    np.asarray([int(uid)], dtype=np.int64)
                )
                uid = str(int(stamp[0]))
        self.directory = os.path.abspath(os.path.join(rel_dir, uid, model_name))
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep,
            keep_period=keep_period,
            best_fn=lambda m: m["episode_return"],
            best_mode="max",
            create=True,
        )
        metadata = dict(metadata or {})
        metadata["checkpointer_version"] = CHECKPOINTER_VERSION
        self._save_interval_steps = int(save_interval_steps)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=options,
            metadata=json.loads(json.dumps(metadata, default=str)),
        )

    def should_save(self, timestep: int, last_issued: Optional[int] = None) -> bool:
        """Whether the manager's save policy (save_interval_steps etc.) will
        accept a save at `timestep`. The pipelined runner checks this BEFORE
        taking the on-device state snapshot, so skipped windows don't pay the
        full-state copy.

        `last_issued` is the step of a save the CALLER has already decided on
        but orbax may not have registered yet (the pipelined loop decides one
        window ahead of issuing): the interval policy is applied against it
        first, since the manager's latest_step is stale until that save
        lands."""
        if (
            last_issued is not None
            and timestep - last_issued < self._save_interval_steps
        ):
            return False
        try:
            return bool(self._manager.should_save(timestep))
        except Exception:  # noqa: BLE001 — older orbax: assume it saves
            return True

    def save(self, timestep: int, state: Any, episode_return: float = 0.0) -> bool:
        """Hand `state` to orbax; serialization may complete asynchronously.

        Callers must pass buffers that no later XLA program donates: the
        Anakin runner saves an on-device SNAPSHOT copy of the learner state
        (systems/runner.py), which is what makes the save safely async — the
        hot path never calls wait()."""
        return self._manager.save(
            timestep,
            args=ocp.args.StandardSave(jax.tree.map(jax.numpy.asarray, state)),
            metrics={"episode_return": float(episode_return)},
        )

    def restore(self, template: Any, timestep: Optional[int] = None) -> Tuple[Any, int]:
        """Restore into the shape/sharding of `template`; returns (state, step)."""
        step = timestep if timestep is not None else self._manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"No checkpoints under {self.directory}")
        restored = self._manager.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        return restored, int(step)

    def get_metadata(self) -> dict:
        meta = self._manager.metadata()
        # Orbax returns a RootMetadata object; the user-provided dict lives in
        # `custom_metadata` (older versions returned the dict directly).
        custom = getattr(meta, "custom_metadata", meta)
        return dict(custom or {})

    def check_version(self) -> None:
        meta = self.get_metadata()
        saved = float(meta.get("checkpointer_version", CHECKPOINTER_VERSION))
        if int(saved) != int(CHECKPOINTER_VERSION):
            raise ValueError(
                f"Checkpoint major version {saved} incompatible with {CHECKPOINTER_VERSION}"
            )

    def wait(self) -> None:
        """Block until in-flight (async) saves complete. NOT on the Anakin hot
        path anymore: the runner saves from a donation-safe snapshot copy, so
        only tests and external callers that need save-visible-on-disk
        ordering (and close()) should call this."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def checkpointer_from_config(config: Any, model_name: str) -> Optional[Checkpointer]:
    ckpt_cfg = config.logger.checkpointing
    if not ckpt_cfg.get("save_model", False):
        return None
    save_args = ckpt_cfg.get("save_args") or {}
    return Checkpointer(
        model_name=model_name,
        metadata=config.to_dict() if hasattr(config, "to_dict") else dict(config),
        checkpoint_uid=save_args.get("checkpoint_uid"),
        save_interval_steps=int(save_args.get("save_interval_steps", 1)),
        max_to_keep=save_args.get("max_to_keep", 1),
        keep_period=save_args.get("keep_period"),
    )
