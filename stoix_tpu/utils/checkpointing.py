"""Orbax-backed checkpointing (reference stoix/utils/checkpointing.py:20-187).

Saves learner state keyed by timestep with best-by-episode-return tracking and
config-as-metadata with a major-version compatibility check. TPU-native
difference from the reference: states are GLOBAL (sharded) arrays — orbax
handles sharded save/restore natively, so there is no unreplicate step
(SURVEY.md §7.1.1).

Resilience (docs/DESIGN.md §2.3): `restore` validates what it loads —
tree-structure against the template plus a finiteness spot-check (leaves
whose TEMPLATE is fully finite must restore fully finite; leaves where the
template itself carries inf/nan sentinels are exempt) — and, when the newest
checkpoint is corrupt or truncated (a preempted save, a chaos-injected
`ckpt_corrupt`), automatically falls back to the newest VALID step instead
of dying on a bare orbax error.
"""

from __future__ import annotations

import json
import os
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from stoix_tpu.resilience.errors import CheckpointIntegrityError

# 2.0: continuous MPO/V-MPO dual variables changed shape from (2,) to
# [2, action_dim] (per-dimension KL constraints) — old checkpoints cannot
# restore into the new template.
# 3.0: PPOLearnerState grew a `kl_beta` leaf (adaptive-KL PPO-penalty state)
# — pre-3.0 PPO/DPO/penalty checkpoints lack it and cannot restore into the
# new template.
CHECKPOINTER_VERSION = 3.0


class Checkpointer:
    def __init__(
        self,
        model_name: str,
        metadata: Optional[dict] = None,
        rel_dir: str = "checkpoints",
        checkpoint_uid: Optional[str] = None,
        save_interval_steps: int = 1,
        max_to_keep: Optional[int] = 1,
        keep_period: Optional[int] = None,
    ):
        import time

        uid = checkpoint_uid
        if uid is None:
            uid = time.strftime("%Y%m%d%H%M%S")
            if jax.process_count() > 1:
                # All processes must agree on the directory (collective save);
                # startup skew can cross a second boundary, so broadcast the
                # coordinator's stamp.
                import numpy as np
                from jax.experimental import multihost_utils

                stamp = multihost_utils.broadcast_one_to_all(
                    np.asarray([int(uid)], dtype=np.int64)
                )
                uid = str(int(stamp[0]))
        self.directory = os.path.abspath(os.path.join(rel_dir, uid, model_name))
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep,
            keep_period=keep_period,
            best_fn=lambda m: m["episode_return"],
            best_mode="max",
            create=True,
        )
        metadata = dict(metadata or {})
        metadata["checkpointer_version"] = CHECKPOINTER_VERSION
        self._save_interval_steps = int(save_interval_steps)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=options,
            metadata=json.loads(json.dumps(metadata, default=str)),
        )

    def should_save(self, timestep: int, last_issued: Optional[int] = None) -> bool:
        """Whether the manager's save policy (save_interval_steps etc.) will
        accept a save at `timestep`. The pipelined runner checks this BEFORE
        taking the on-device state snapshot, so skipped windows don't pay the
        full-state copy.

        `last_issued` is the step of a save the CALLER has already decided on
        but orbax may not have registered yet (the pipelined loop decides one
        window ahead of issuing): the interval policy is applied against it
        first, since the manager's latest_step is stale until that save
        lands."""
        if (
            last_issued is not None
            and timestep - last_issued < self._save_interval_steps
        ):
            return False
        try:
            return bool(self._manager.should_save(timestep))
        except Exception:  # noqa: BLE001 — older orbax: assume it saves
            return True

    def save(
        self,
        timestep: int,
        state: Any,
        episode_return: float = 0.0,
        force: bool = False,
    ) -> bool:
        """Hand `state` to orbax; serialization may complete asynchronously.

        Callers must pass buffers that no later XLA program donates: the
        Anakin runner saves an on-device SNAPSHOT copy of the learner state
        (systems/runner.py), which is what makes the save safely async — the
        hot path never calls wait(). `force=True` bypasses the save-interval
        policy (the preemption handler's emergency checkpoint must land
        regardless of cadence)."""
        saved = self._manager.save(
            timestep,
            args=ocp.args.StandardSave(jax.tree.map(jax.numpy.asarray, state)),
            metrics={"episode_return": float(episode_return)},
            force=force,
        )
        # Chaos hook (`STOIX_TPU_FAULT=ckpt_corrupt`, one-shot): mangle this
        # step's files AFTER serialization completes, so the restore-fallback
        # path is exercised against a real on-disk layout.
        from stoix_tpu.resilience import faultinject

        if saved and faultinject.consume_ckpt_corrupt():
            self._manager.wait_until_finished()
            faultinject.corrupt_checkpoint_files(
                os.path.join(self.directory, str(timestep))
            )
        return saved

    def all_steps(self) -> List[int]:
        """Ascending steps with a checkpoint on disk."""
        return sorted(int(s) for s in self._manager.all_steps())

    @staticmethod
    def _validate(restored: Any, template: Any, step: int) -> None:
        """Integrity gate: identical tree structure, and every float leaf
        whose TEMPLATE is fully finite must restore fully finite. Template
        leaves that legitimately carry inf/nan (masks, bound sentinels) are
        exempt — the template defines what 'finite' means for this state."""
        got = jax.tree.structure(restored)
        want = jax.tree.structure(template)
        if got != want:
            raise CheckpointIntegrityError(
                step, f"tree structure mismatch: restored {got} != template {want}"
            )
        def _as_float_array(leaf: Any):
            """Host float array for finiteness checks, or None for non-float
            leaves. jnp.issubdtype (not np.) so ml_dtypes floats — bfloat16,
            the common TPU param dtype — are validated, not skipped; they are
            widened to float32 because numpy ufuncs don't cover them."""
            arr = np.asarray(leaf)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                return None
            if arr.dtype not in (np.float16, np.float32, np.float64):
                arr = arr.astype(np.float32)
            return arr

        restored_leaves = jax.tree_util.tree_flatten_with_path(restored)[0]
        template_leaves = jax.tree.leaves(template)
        for (path, leaf), ref in zip(restored_leaves, template_leaves):
            if not getattr(leaf, "is_fully_addressable", True):
                continue  # multi-host shard not local to this process
            arr = _as_float_array(leaf)
            if arr is None or np.isfinite(arr).all():
                continue
            ref_arr = _as_float_array(ref)
            if ref_arr is not None and not np.isfinite(ref_arr).all():
                continue  # the template itself carries non-finite sentinels
            raise CheckpointIntegrityError(
                step,
                f"non-finite values in leaf {jax.tree_util.keystr(path)} "
                f"(template expects finite values here)",
            )

    def restore(
        self,
        template: Any,
        timestep: Optional[int] = None,
        validate: bool = True,
        fallback: bool = True,
    ) -> Tuple[Any, int]:
        """Restore into the shape/sharding of `template`; returns (state, step).

        Latest-step restores walk newest-to-oldest past corrupt/truncated/
        non-finite checkpoints (each rejection logged) until one validates —
        a preempted or chaos-corrupted save costs one checkpoint interval,
        not the run. An EXPLICIT `timestep` never falls back: a missing step
        raises FileNotFoundError listing what IS available, and a corrupt one
        raises its own error (the caller asked for that step by name)."""
        from stoix_tpu.observability import get_logger

        steps = self.all_steps()
        if timestep is not None:
            if int(timestep) not in steps:
                raise FileNotFoundError(
                    f"No checkpoint at timestep {timestep} under "
                    f"{self.directory}; available steps: {steps or '[]'}"
                )
            candidates = [int(timestep)]
            fallback = False
        else:
            if not steps:
                raise FileNotFoundError(f"No checkpoints under {self.directory}")
            candidates = steps[::-1]

        last_error: Optional[Exception] = None
        for step in candidates:
            try:
                restored = self._manager.restore(
                    step, args=ocp.args.StandardRestore(template)
                )
                if validate:
                    self._validate(restored, template, step)
                return restored, int(step)
            except Exception as exc:  # noqa: BLE001 — each candidate's failure
                # mode differs (orbax I/O error, msgpack truncation, integrity
                # rejection); all mean "try the next-newest".
                if not fallback:
                    raise
                last_error = exc
                get_logger("stoix_tpu.checkpoint").warning(
                    "[checkpoint] step %d unusable (%s: %s) — falling back to "
                    "the next-newest checkpoint",
                    step, type(exc).__name__, exc,
                )
        raise CheckpointIntegrityError(
            candidates[-1],
            f"no valid checkpoint among steps {candidates} under "
            f"{self.directory}; last error: {type(last_error).__name__}: {last_error}",
        )

    def get_metadata(self) -> dict:
        meta = self._manager.metadata()
        # Orbax returns a RootMetadata object; the user-provided dict lives in
        # `custom_metadata` (older versions returned the dict directly).
        custom = getattr(meta, "custom_metadata", meta)
        return dict(custom or {})

    def check_version(self) -> None:
        meta = self.get_metadata()
        saved = float(meta.get("checkpointer_version", CHECKPOINTER_VERSION))
        if int(saved) != int(CHECKPOINTER_VERSION):
            raise ValueError(
                f"Checkpoint major version {saved} incompatible with {CHECKPOINTER_VERSION}"
            )

    def wait(self) -> None:
        """Block until in-flight (async) saves complete. NOT on the Anakin hot
        path anymore: the runner saves from a donation-safe snapshot copy, so
        only tests and external callers that need save-visible-on-disk
        ordering (and close()) should call this."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def checkpointer_from_config(config: Any, model_name: str) -> Optional[Checkpointer]:
    ckpt_cfg = config.logger.checkpointing
    if not ckpt_cfg.get("save_model", False):
        return None
    save_args = ckpt_cfg.get("save_args") or {}
    return Checkpointer(
        model_name=model_name,
        metadata=config.to_dict() if hasattr(config, "to_dict") else dict(config),
        checkpoint_uid=save_args.get("checkpoint_uid"),
        save_interval_steps=int(save_args.get("save_interval_steps", 1)),
        max_to_keep=save_args.get("max_to_keep", 1),
        keep_period=save_args.get("keep_period"),
    )
