"""Orbax-backed checkpointing (reference stoix/utils/checkpointing.py:20-187).

Saves learner state keyed by timestep with best-by-episode-return tracking and
config-as-metadata with a major-version compatibility check. TPU-native
difference from the reference: states are GLOBAL (sharded) arrays — orbax
handles sharded save/restore natively, so there is no unreplicate step
(SURVEY.md §7.1.1).

Resilience (docs/DESIGN.md §2.3): `restore` validates what it loads —
tree-structure against the template plus a finiteness spot-check (leaves
whose TEMPLATE is fully finite must restore fully finite; leaves where the
template itself carries inf/nan sentinels are exempt) — and, when the newest
checkpoint is corrupt or truncated (a preempted save, a chaos-injected
`ckpt_corrupt`), automatically falls back to the newest VALID step instead
of dying on a bare orbax error.

Topology-elastic restore (docs/DESIGN.md §2.4): every save records its device
footprint (the number of distinct devices the state's shardings span) in a
`_topology.json` sidecar next to the step directories, plus the saving
process's device/process counts in the manager metadata. When `restore` sees
a template whose footprint differs from the saved one — a run saved on an
8-device mesh resuming on 1 device, or vice versa — it takes the RESHARD
path: materialize the checkpoint to host WITHOUT a sharded template, match
leaves to the template by tree-path (orbax serializes NamedTuples as dicts,
so leaf ORDER differs), validate shape/dtype, and re-place each leaf via the
template's own NamedShardings (the fresh setup built them from
`parallel.mesh`). Values pass through the host unchanged: params restore
bit-identical. Leaves whose GLOBAL shape is topology-dependent (the
per-shard RNG key state, shaped [num_shards, ...]) cannot be ported; they
keep the template's freshly-initialized value and are logged loudly.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from stoix_tpu.resilience.errors import CheckpointIntegrityError

# 2.0: continuous MPO/V-MPO dual variables changed shape from (2,) to
# [2, action_dim] (per-dimension KL constraints) — old checkpoints cannot
# restore into the new template.
# 3.0: PPOLearnerState grew a `kl_beta` leaf (adaptive-KL PPO-penalty state)
# — pre-3.0 PPO/DPO/penalty checkpoints lack it and cannot restore into the
# new template.
CHECKPOINTER_VERSION = 3.0

# Sidecar recording each step's device footprint (docs/DESIGN.md §2.4):
# {"steps": {"<step>": {"devices": N}}}. Lives at the store root next to the
# step directories; orbax's step scan only considers directories, so the
# file is invisible to it.
TOPOLOGY_SIDECAR = "_topology.json"

# Sidecar recording each step's per-leaf sha256 digests (docs/DESIGN.md
# §2.9): {"steps": {"<step>": {"<slash-joined tree path>": "<hex>"}}}.
# Written by save() from the exact host bytes orbax serializes; restore()
# recomputes digests from what came back and REJECTS the step on mismatch
# (on-disk bit-rot walks to the next-newest checkpoint instead of resuming
# as garbage). Shares the digest helpers with the fleet emergency store and
# the serving canary (resilience/integrity.py).
DIGEST_SIDECAR = "_digests.json"


def saved_digest_record(store_dir: str) -> Dict[int, Dict[str, str]]:
    """Per-step digest records from a store's `_digests.json` ({} when
    absent). Module-level so the serving loader (stoix_tpu/serve) can verify
    a store it reads without constructing a Checkpointer."""
    try:
        with open(os.path.join(str(store_dir), DIGEST_SIDECAR)) as f:
            data = json.load(f)
        return {
            int(step): {str(k): str(v) for k, v in (record or {}).items()}
            for step, record in (data.get("steps") or {}).items()
        }
    except (OSError, ValueError):
        return {}


def _device_footprint(tree: Any) -> Optional[int]:
    """Number of distinct devices the tree's jax.Array leaves span, or None
    when the tree carries no addressable device arrays (host/numpy state)."""
    ids = set()
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                ids.update(d.id for d in leaf.sharding.device_set)
            except Exception:  # noqa: BLE001 — deleted/donated arrays have no sharding
                continue
    return len(ids) or None


def _path_key(path: Any) -> Tuple[str, ...]:
    """Normalize a jax key-path so the same LOGICAL leaf matches across
    container types: orbax serializes NamedTuples as dicts (GetAttrKey on the
    template side, DictKey on the restored side) and tuples as lists."""
    parts = []
    for entry in path:
        if hasattr(entry, "name"):  # GetAttrKey (NamedTuple/dataclass field)
            parts.append(str(entry.name))
        elif hasattr(entry, "key"):  # DictKey / FlattenedIndexKey
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):  # SequenceKey
            parts.append(str(entry.idx))
        else:
            parts.append(str(entry))
    return tuple(parts)


def place_host_leaves(
    raw_by_path: Dict[Tuple[str, ...], Any],
    template: Any,
    step: int,
    allow_missing: bool = False,
) -> Tuple[Any, int, List[str], List[Tuple[str, ...]]]:
    """Place host-materialized leaves into `template`'s structure and
    shardings, matching by normalized tree-path — the placement half of the
    topology-elastic restore (docs/DESIGN.md §2.4), shared with the fleet
    local-shard emergency restore (resilience/fleet.py, §2.6).

    Returns (tree, matched_count, reinitialized_descriptions,
    reinitialized_keys) — the keys let digest verification (§2.9) skip
    leaves that deliberately kept the template's fresh value. Shape
    mismatches are topology-dependent state and keep the template's value;
    dtype mismatches raise CheckpointIntegrityError (corruption, not
    topology). A missing leaf raises unless `allow_missing` (the fleet store
    legitimately omits partially-addressable leaves); zero matched leaves is
    always an error — that is a different state, not a topology change."""
    template_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    placed: List[Any] = []
    reinitialized: List[str] = []
    reinitialized_keys: List[Tuple[str, ...]] = []
    matched = 0
    for path, ref in template_leaves:
        key = _path_key(path)
        if key not in raw_by_path:
            if allow_missing:
                reinitialized.append(
                    f"{jax.tree_util.keystr(path)} (absent from the store)"
                )
                reinitialized_keys.append(key)
                placed.append(ref)
                continue
            raise CheckpointIntegrityError(
                step,
                f"leaf {jax.tree_util.keystr(path)} missing from the "
                f"checkpoint (resharded restore matches by tree-path)",
            )
        arr = np.asarray(raw_by_path[key])
        ref_dtype = getattr(ref, "dtype", None) or np.asarray(ref).dtype
        ref_shape = tuple(np.shape(ref))
        if arr.dtype != ref_dtype:
            raise CheckpointIntegrityError(
                step,
                f"dtype mismatch at {jax.tree_util.keystr(path)}: saved "
                f"{arr.dtype} vs template {ref_dtype}",
            )
        if arr.shape != ref_shape:
            # Topology-dependent global shape (e.g. the [num_shards, ...]
            # per-shard key state): not portable across meshes by
            # construction — keep the template's fresh value.
            reinitialized.append(
                f"{jax.tree_util.keystr(path)} (saved {arr.shape} vs "
                f"template {ref_shape})"
            )
            reinitialized_keys.append(key)
            placed.append(ref)
            continue
        matched += 1
        if isinstance(ref, jax.Array):
            placed.append(jax.device_put(arr, ref.sharding))
        else:
            placed.append(arr)
    if matched == 0:
        raise CheckpointIntegrityError(
            step,
            "resharded restore matched ZERO leaves by shape — this is a "
            "different state entirely, not a topology change",
        )
    return treedef.unflatten(placed), matched, reinitialized, reinitialized_keys


def read_host_leaves(store_dir: str, step: int) -> Dict[Tuple[str, ...], Any]:
    """Materialize one checkpoint step to HOST numpy leaves keyed by
    normalized tree-path — the read half of the topology-elastic restore
    (docs/DESIGN.md §2.4), shared with the serving path (stoix_tpu/serve/
    checkpoint.py), which restores a params SUBTREE onto whatever device
    topology the server runs.

    Reads through a standalone PyTree handler with restore_type=ndarray: the
    MANAGER's restore (with or without a template) reconstructs jax.Arrays on
    the devices recorded AT SAVE TIME, which need not exist on the restoring
    host — forcing numpy never touches device placement."""
    step_path = os.path.join(store_dir, str(step), "default")
    if not os.path.isdir(step_path):  # older orbax layouts: no item subdir
        step_path = os.path.join(store_dir, str(step))
    reader = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    try:
        raw_meta = reader.metadata(step_path)
        restore_args = jax.tree.map(
            lambda _m: ocp.RestoreArgs(restore_type=np.ndarray), raw_meta
        )
        raw = reader.restore(
            step_path, args=ocp.args.PyTreeRestore(restore_args=restore_args)
        )
    finally:
        reader.close()
    return {
        _path_key(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(raw)[0]
    }


class Checkpointer:
    def __init__(
        self,
        model_name: str,
        metadata: Optional[dict] = None,
        rel_dir: str = "checkpoints",
        checkpoint_uid: Optional[str] = None,
        save_interval_steps: int = 1,
        max_to_keep: Optional[int] = 1,
        keep_period: Optional[int] = None,
    ):
        import time

        uid = checkpoint_uid
        if uid is None:
            uid = time.strftime("%Y%m%d%H%M%S")
            if jax.process_count() > 1:
                # All processes must agree on the directory (collective save);
                # startup skew can cross a second boundary, so broadcast the
                # coordinator's stamp.
                import numpy as np
                from jax.experimental import multihost_utils

                stamp = multihost_utils.broadcast_one_to_all(
                    np.asarray([int(uid)], dtype=np.int64)
                )
                uid = str(int(stamp[0]))
        self.directory = os.path.abspath(os.path.join(rel_dir, uid, model_name))
        options = ocp.CheckpointManagerOptions(
            save_interval_steps=save_interval_steps,
            max_to_keep=max_to_keep,
            keep_period=keep_period,
            best_fn=lambda m: m["episode_return"],
            best_mode="max",
            create=True,
        )
        metadata = dict(metadata or {})
        metadata["checkpointer_version"] = CHECKPOINTER_VERSION
        # Saving process's topology, for operators reading the store; the
        # per-step footprint that drives elastic restore lives in the
        # _topology.json sidecar (written by save — only then is the actual
        # device span of the state known).
        metadata["topology"] = {
            "device_count": jax.device_count(),
            "process_count": jax.process_count(),
        }
        self._save_interval_steps = int(save_interval_steps)
        # Typed rejection log of the most recent restore()'s fallback walk
        # (docs/DESIGN.md §2.9): [{"step", "reason", "error"}, ...].
        self.last_restore_report: List[Dict[str, str]] = []
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=options,
            metadata=json.loads(json.dumps(metadata, default=str)),
        )

    def should_save(self, timestep: int, last_issued: Optional[int] = None) -> bool:
        """Whether the manager's save policy (save_interval_steps etc.) will
        accept a save at `timestep`. The pipelined runner checks this BEFORE
        taking the on-device state snapshot, so skipped windows don't pay the
        full-state copy.

        `last_issued` is the step of a save the CALLER has already decided on
        but orbax may not have registered yet (the pipelined loop decides one
        window ahead of issuing): the interval policy is applied against it
        first, since the manager's latest_step is stale until that save
        lands."""
        if (
            last_issued is not None
            and timestep - last_issued < self._save_interval_steps
        ):
            return False
        try:
            return bool(self._manager.should_save(timestep))
        except Exception:  # noqa: BLE001 — older orbax: assume it saves
            return True

    def save(
        self,
        timestep: int,
        state: Any,
        episode_return: float = 0.0,
        force: bool = False,
    ) -> bool:
        """Hand `state` to orbax; serialization may complete asynchronously.

        Callers must pass buffers that no later XLA program donates: the
        Anakin runner saves an on-device SNAPSHOT copy of the learner state
        (systems/runner.py), which is what makes the save safely async — the
        hot path never calls wait(). `force=True` bypasses the save-interval
        policy (the preemption handler's emergency checkpoint must land
        regardless of cadence)."""
        footprint = _device_footprint(state)
        saved = self._manager.save(
            timestep,
            args=ocp.args.StandardSave(jax.tree.map(jax.numpy.asarray, state)),
            metrics={"episode_return": float(episode_return)},
            force=force,
        )
        if saved and jax.process_index() == 0:
            self._record_topology(timestep, footprint)
            self._record_digests(timestep, state)
        # Chaos hook (`STOIX_TPU_FAULT=ckpt_corrupt`, one-shot): mangle this
        # step's files AFTER serialization completes, so the restore-fallback
        # path is exercised against a real on-disk layout.
        from stoix_tpu.resilience import faultinject

        if saved and faultinject.consume_ckpt_corrupt():
            self._manager.wait_until_finished()
            faultinject.corrupt_checkpoint_files(
                os.path.join(self.directory, str(timestep))
            )
        return saved

    def all_steps(self) -> List[int]:
        """Ascending steps with a checkpoint on disk."""
        return sorted(int(s) for s in self._manager.all_steps())

    # -- topology sidecar ----------------------------------------------------
    def _sidecar_path(self) -> str:
        return os.path.join(self.directory, TOPOLOGY_SIDECAR)

    def _record_topology(self, timestep: int, footprint: Optional[int]) -> None:
        """Read-modify-write the per-step footprint sidecar. Best-effort: a
        missing sidecar only disables the PROACTIVE reshard decision (restore
        still falls back to resharding when the template path fails)."""
        if footprint is None:
            return
        try:
            record = self.saved_topologies()
            record[int(timestep)] = {"devices": int(footprint)}
            with open(self._sidecar_path(), "w") as f:
                json.dump(
                    {"steps": {str(k): v for k, v in sorted(record.items())}}, f
                )
        except OSError as exc:
            from stoix_tpu.observability import get_logger

            get_logger("stoix_tpu.checkpoint").warning(
                "[checkpoint] could not record topology sidecar for step %d "
                "(%s) — elastic restore will rely on its fallback path",
                timestep, exc,
            )

    def saved_topologies(self) -> Dict[int, dict]:
        """Per-step device footprints from the sidecar ({} when absent)."""
        try:
            with open(self._sidecar_path()) as f:
                data = json.load(f)
            return {int(k): dict(v) for k, v in (data.get("steps") or {}).items()}
        except (OSError, ValueError):
            return {}

    # -- digest sidecar (docs/DESIGN.md §2.9) --------------------------------
    def _record_digests(self, timestep: int, state: Any) -> None:
        """Record per-leaf sha256 digests of the exact host bytes orbax is
        serializing for `timestep` (read-modify-write; entries for steps the
        retention policy deleted are pruned). Best-effort like the topology
        sidecar: a missing record only disables digest VERIFICATION for this
        step — restore still runs its structural + finiteness gates.

        Cost: one device->host materialization of the snapshot per save —
        paid on the overlapped host half of the pipelined runner, never on
        the device stream. Leaves not fully addressable from this process
        (multi-host shards) are skipped and simply not verified."""
        from stoix_tpu.resilience import integrity

        try:
            digests: Dict[str, str] = {}
            for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
                if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                    continue
                digests["/".join(_path_key(path))] = integrity.leaf_digest(
                    np.asarray(leaf)
                )
            record = self.saved_digests()
            record[int(timestep)] = digests
            try:
                on_disk = set(self._manager.all_steps())
            except Exception:  # noqa: BLE001 — pruning is housekeeping only
                on_disk = set(record)
            keep = {step for step in record if step in on_disk or step == int(timestep)}
            path = os.path.join(self.directory, DIGEST_SIDECAR)
            with open(path, "w") as f:
                json.dump(
                    {
                        "steps": {
                            str(step): record[step] for step in sorted(keep)
                        }
                    },
                    f,
                )
        except OSError as exc:
            from stoix_tpu.observability import get_logger

            get_logger("stoix_tpu.checkpoint").warning(
                "[checkpoint] could not record digest sidecar for step %d "
                "(%s) — this step will restore without digest verification",
                timestep, exc,
            )

    def saved_digests(self) -> Dict[int, Dict[str, str]]:
        """Per-step digest records from this store's sidecar ({} = none)."""
        return saved_digest_record(self.directory)

    def _verify_digests(
        self, restored: Any, step: int, skip_keys: Optional[set] = None
    ) -> None:
        """Recompute each restored leaf's digest and compare against the
        record made at save time; a mismatch is on-disk bit-rot and raises
        the typed 'digest' rejection (the fallback walk tries the next-
        newest step). `skip_keys` excludes leaves the elastic restore
        deliberately reinitialized from the template. No record for this
        step (pre-digest store, sidecar lost) = skip, logged at debug."""
        from stoix_tpu.resilience import integrity

        record = self.saved_digests().get(int(step)) or {}
        if not record:
            return
        skip = skip_keys or set()
        arrays: Dict[str, np.ndarray] = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(restored)[0]:
            key = _path_key(path)
            if key in skip:
                continue
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                continue
            arrays["/".join(key)] = np.asarray(leaf)
        mismatched = integrity.verify_digests(arrays, record)
        if mismatched:
            raise CheckpointIntegrityError(
                step,
                f"sha256 digest mismatch on {len(mismatched)} leaf(s) — the "
                f"bytes on disk are not the bytes that were saved (bit-rot "
                f"or tampering): {', '.join(mismatched[:5])}"
                f"{'...' if len(mismatched) > 5 else ''}",
                kind="digest",
            )

    @staticmethod
    def _validate(restored: Any, template: Any, step: int) -> None:
        """Integrity gate: identical tree structure, and every float leaf
        whose TEMPLATE is fully finite must restore fully finite. Template
        leaves that legitimately carry inf/nan (masks, bound sentinels) are
        exempt — the template defines what 'finite' means for this state."""
        got = jax.tree.structure(restored)
        want = jax.tree.structure(template)
        if got != want:
            raise CheckpointIntegrityError(
                step,
                f"tree structure mismatch: restored {got} != template {want}",
                kind="structure",
            )
        def _as_float_array(leaf: Any):
            """Host float array for finiteness checks, or None for non-float
            leaves. jnp.issubdtype (not np.) so ml_dtypes floats — bfloat16,
            the common TPU param dtype — are validated, not skipped; they are
            widened to float32 because numpy ufuncs don't cover them."""
            arr = np.asarray(leaf)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                return None
            if arr.dtype not in (np.float16, np.float32, np.float64):
                arr = arr.astype(np.float32)
            return arr

        restored_leaves = jax.tree_util.tree_flatten_with_path(restored)[0]
        template_leaves = jax.tree.leaves(template)
        for (path, leaf), ref in zip(restored_leaves, template_leaves):
            if not getattr(leaf, "is_fully_addressable", True):
                continue  # multi-host shard not local to this process
            arr = _as_float_array(leaf)
            if arr is None or np.isfinite(arr).all():
                continue
            ref_arr = _as_float_array(ref)
            if ref_arr is not None and not np.isfinite(ref_arr).all():
                continue  # the template itself carries non-finite sentinels
            raise CheckpointIntegrityError(
                step,
                f"non-finite values in leaf {jax.tree_util.keystr(path)} "
                f"(template expects finite values here)",
                kind="non_finite",
            )

    def _restore_resharded(self, step: int, template: Any) -> Tuple[Any, set]:
        """Topology-elastic restore path (docs/DESIGN.md §2.4): materialize
        the checkpoint to host with NO sharded template, match leaves to the
        template by normalized tree-path, and re-place each onto the
        template's own sharding. Values round-trip through the host
        untouched — params restore bit-identical across meshes. Returns
        (tree, reinitialized_key_set) so digest verification skips the
        leaves that deliberately kept the template's fresh value.

        Shape-mismatched leaves are topology-dependent state (the per-shard
        RNG keys, [num_shards, ...]): they keep the TEMPLATE's value and are
        logged. dtype mismatches and missing leaves are corruption, not
        topology — they raise CheckpointIntegrityError."""
        from stoix_tpu.observability import get_logger

        raw_by_path = read_host_leaves(self.directory, step)
        restored, matched, reinitialized, reinit_keys = place_host_leaves(
            raw_by_path, template, step
        )
        if reinitialized:
            get_logger("stoix_tpu.checkpoint").warning(
                "[checkpoint] elastic restore of step %d re-placed %d leaf(s) "
                "onto the new mesh; %d topology-dependent leaf(s) kept their "
                "template initialization: %s",
                step, matched, len(reinitialized), "; ".join(reinitialized),
            )
        return restored, set(reinit_keys)

    def restore(
        self,
        template: Any,
        timestep: Optional[int] = None,
        validate: bool = True,
        fallback: bool = True,
        reshard: str = "auto",
    ) -> Tuple[Any, int]:
        """Restore into the shape/sharding of `template`; returns (state, step).

        Latest-step restores walk newest-to-oldest past corrupt/truncated/
        non-finite/digest-mismatched checkpoints until one validates — a
        preempted, chaos-corrupted, or bit-rotted save costs one checkpoint
        interval, not the run. Each rejection is logged with its DISTINCT
        typed reason ('structure' | 'non_finite' | 'digest' | the raising
        exception's type) and recorded in `self.last_restore_report`
        (docs/DESIGN.md §2.9; the runner surfaces the count as
        LAST_RUN_STATS.resilience.restore_skipped). An EXPLICIT `timestep`
        never falls back: a missing step raises FileNotFoundError listing
        what IS available, and a corrupt one raises its own error (the
        caller asked for that step by name).

        `reshard` controls topology elasticity (docs/DESIGN.md §2.4):
        'auto' (default) takes the resharding path when the sidecar-recorded
        footprint of a step differs from the template's — and additionally
        retries a failed template-path restore through it (old stores have no
        sidecar); 'never' restores strictly into the template's topology;
        'force' always reshards through the host."""
        from stoix_tpu.observability import get_logger

        if reshard not in ("auto", "never", "force"):
            raise ValueError(f"reshard must be auto|never|force, got {reshard!r}")
        self.last_restore_report: List[Dict[str, str]] = []
        steps = self.all_steps()
        if timestep is not None:
            if int(timestep) not in steps:
                raise FileNotFoundError(
                    f"No checkpoint at timestep {timestep} under "
                    f"{self.directory}; available steps: {steps or '[]'}"
                )
            candidates = [int(timestep)]
            fallback = False
        else:
            if not steps:
                raise FileNotFoundError(f"No checkpoints under {self.directory}")
            candidates = steps[::-1]

        saved_topologies = self.saved_topologies() if reshard == "auto" else {}
        template_footprint = _device_footprint(template)
        log = get_logger("stoix_tpu.checkpoint")
        last_error: Optional[Exception] = None
        for step in candidates:
            saved_fp = (saved_topologies.get(step) or {}).get("devices")
            proactive_reshard = reshard == "force" or (
                reshard == "auto"
                and saved_fp is not None
                and template_footprint is not None
                and int(saved_fp) != int(template_footprint)
            )
            try:
                digest_skip: set = set()
                if proactive_reshard:
                    log.info(
                        "[checkpoint] step %d saved on %s device(s), template "
                        "spans %s — taking the elastic (resharding) restore "
                        "path", step, saved_fp or "?", template_footprint,
                    )
                    restored, digest_skip = self._restore_resharded(step, template)
                else:
                    try:
                        restored = self._manager.restore(
                            step, args=ocp.args.StandardRestore(template)
                        )
                    except (CheckpointIntegrityError, FileNotFoundError):
                        raise
                    except Exception as exc:  # noqa: BLE001 — template-path
                        # restore failures on an UNKNOWN-topology store (no
                        # sidecar entry) are often sharding mismatches: give
                        # the elastic path one shot before rejecting the step.
                        # A KNOWN-matching topology that failed is corruption
                        # — re-reading the whole state through the host path
                        # would double the I/O for nothing.
                        if reshard != "auto" or saved_fp is not None:
                            raise
                        log.warning(
                            "[checkpoint] template-path restore of step %d "
                            "failed (%s: %s) — retrying through the elastic "
                            "resharding path", step, type(exc).__name__, exc,
                        )
                        restored, digest_skip = self._restore_resharded(
                            step, template
                        )
                if validate:
                    self._validate(restored, template, step)
                    self._verify_digests(restored, step, skip_keys=digest_skip)
                return restored, int(step)
            except Exception as exc:  # noqa: BLE001 — each candidate's failure
                # mode differs (orbax I/O error, msgpack truncation, integrity
                # rejection, digest mismatch); all mean "try the next-newest",
                # each with its DISTINCT typed reason in the log + report.
                if not fallback:
                    raise
                last_error = exc
                reason = getattr(exc, "kind", None) or type(exc).__name__
                self.last_restore_report.append(
                    {"step": str(step), "reason": str(reason), "error": str(exc)}
                )
                log.warning(
                    "[checkpoint] step %d unusable [reason: %s] (%s: %s) — "
                    "falling back to the next-newest checkpoint",
                    step, reason, type(exc).__name__, exc,
                )
        raise CheckpointIntegrityError(
            candidates[-1],
            f"no valid checkpoint among steps {candidates} under "
            f"{self.directory}; last error: {type(last_error).__name__}: {last_error}",
        )

    def get_metadata(self) -> dict:
        meta = self._manager.metadata()
        # Orbax returns a RootMetadata object; the user-provided dict lives in
        # `custom_metadata` (older versions returned the dict directly).
        custom = getattr(meta, "custom_metadata", meta)
        return dict(custom or {})

    def check_version(self) -> None:
        meta = self.get_metadata()
        saved = float(meta.get("checkpointer_version", CHECKPOINTER_VERSION))
        if int(saved) != int(CHECKPOINTER_VERSION):
            raise ValueError(
                f"Checkpoint major version {saved} incompatible with {CHECKPOINTER_VERSION}"
            )

    def wait(self) -> None:
        """Block until in-flight (async) saves complete. NOT on the Anakin hot
        path anymore: the runner saves from a donation-safe snapshot copy, so
        only tests and external callers that need save-visible-on-disk
        ordering (and close()) should call this."""
        self._manager.wait_until_finished()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()


def checkpointer_from_config(config: Any, model_name: str) -> Optional[Checkpointer]:
    ckpt_cfg = config.logger.checkpointing
    if not ckpt_cfg.get("save_model", False):
        return None
    save_args = ckpt_cfg.get("save_args") or {}
    return Checkpointer(
        model_name=model_name,
        metadata=config.to_dict() if hasattr(config, "to_dict") else dict(config),
        checkpoint_uid=save_args.get("checkpoint_uid"),
        save_interval_steps=int(save_args.get("save_interval_steps", 1)),
        max_to_keep=save_args.get("max_to_keep", 1),
        keep_period=save_args.get("keep_period"),
    )
