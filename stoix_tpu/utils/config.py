"""First-party config system — the Hydra/OmegaConf equivalent.

The reference composes a Hydra config tree (reference stoix/configs/**, entry
points like stoix/systems/ppo/anakin/ff_ppo.py:709-731); this module provides
the same developer surface without the dependency:

  - `Config`: attribute-access nested dict (OmegaConf.DictConfig equivalent,
    permanently "struct off" — systems inject computed fields freely).
  - YAML group composition: a root file's `defaults:` list pulls group files
    (e.g. ``- env: cartpole``) whose content lands under the group key.
  - CLI overrides: ``group=name`` re-selects a group file, ``a.b.c=value``
    sets a dotted path (values parsed as YAML).
  - `instantiate(cfg)`: builds objects from `_target_` dotted paths,
    recursively (hydra.utils.instantiate equivalent), with `_partial_` support.

Example:

    config = compose(config_dir, "default/anakin/default_ff_ppo.yaml",
                     ["env=pendulum", "system.gamma=0.99"])
"""

from __future__ import annotations

import copy
import importlib
import os
from typing import Any, Dict, List, Optional, Sequence

import yaml


class Config(dict):
    """A nested dict with attribute access. Always mutable ("struct off")."""

    def __getattr__(self, item: str) -> Any:
        try:
            return self[item]
        except KeyError as e:
            raise AttributeError(item) from e

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __delattr__(self, key: str) -> None:
        del self[key]

    def __deepcopy__(self, memo: dict) -> "Config":
        return Config({k: copy.deepcopy(v, memo) for k, v in self.items()})

    @staticmethod
    def from_dict(d: Any) -> Any:
        if isinstance(d, dict):
            return Config({k: Config.from_dict(v) for k, v in d.items()})
        if isinstance(d, list):
            return [Config.from_dict(v) for v in d]
        return d

    def to_dict(self) -> Dict[str, Any]:
        def conv(v: Any) -> Any:
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, list):
                return [conv(x) for x in v]
            return v

        return conv(self)


def _deep_merge(base: Dict[str, Any], overlay: Dict[str, Any]) -> Dict[str, Any]:
    """Merge overlay into base (overlay wins; dicts merge recursively)."""
    out = dict(base)
    for k, v in overlay.items():
        if k in out and isinstance(out[k], dict) and isinstance(v, dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _load_yaml(path: str) -> Dict[str, Any]:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f"Config file {path} must contain a mapping at top level")
    return data


def _resolve_group_file(config_dir: str, group: str, name: str) -> str:
    for candidate in (
        os.path.join(config_dir, group, f"{name}.yaml"),
        os.path.join(config_dir, group, name, "default.yaml"),
    ):
        if os.path.exists(candidate):
            return candidate
    raise FileNotFoundError(
        f"No config file for group '{group}' name '{name}' under {config_dir}"
    )


def _set_dotted(cfg: Dict[str, Any], dotted: str, value: Any) -> None:
    keys = dotted.split(".")
    node = cfg
    for k in keys[:-1]:
        if k not in node or not isinstance(node[k], dict):
            node[k] = {}
        node = node[k]
    node[keys[-1]] = value


def _parse_value(raw: str) -> Any:
    try:
        return yaml.safe_load(raw)
    except yaml.YAMLError:
        return raw


def compose(
    config_dir: str,
    root_file: str,
    overrides: Optional[Sequence[str]] = None,
) -> Config:
    """Compose a config from a root file's defaults list plus CLI overrides."""
    overrides = list(overrides or [])
    root_path = os.path.join(config_dir, root_file)
    root = _load_yaml(root_path)
    defaults: List[Any] = root.pop("defaults", [])

    # Group overrides (``env=pendulum``) redirect defaults-list entries; they
    # must be applied before files are loaded.
    group_overrides: Dict[str, str] = {}
    value_overrides: List[str] = []
    for ov in overrides:
        if "=" not in ov:
            raise ValueError(f"Override '{ov}' must be key=value")
        key, raw = ov.split("=", 1)
        if "." not in key and any(
            isinstance(d, dict) and key in d for d in defaults
        ):
            group_overrides[key] = raw
        else:
            value_overrides.append(ov)

    merged: Dict[str, Any] = {}
    self_merged = False
    for entry in defaults:
        if entry == "_self_":
            merged = _deep_merge(merged, root)
            self_merged = True
            continue
        if not isinstance(entry, dict) or len(entry) != 1:
            raise ValueError(f"Unsupported defaults entry: {entry!r}")
        group, name = next(iter(entry.items()))
        name = group_overrides.get(group, name)
        path = _resolve_group_file(config_dir, group, str(name))
        content = _load_yaml(path)
        content.pop("defaults", None)
        merged = _deep_merge(merged, {group: content})
    if not self_merged:
        merged = _deep_merge(merged, root)

    for ov in value_overrides:
        key, raw = ov.split("=", 1)
        _set_dotted(merged, key, _parse_value(raw))

    return Config.from_dict(merged)


def default_config_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "configs")


def _import_target(target: str) -> Any:
    module_name, _, attr = target.rpartition(".")
    if not module_name:
        raise ValueError(f"_target_ '{target}' must be a dotted path")
    module = importlib.import_module(module_name)
    return getattr(module, attr)


def instantiate(cfg: Any, **kwargs: Any) -> Any:
    """Recursively build objects from configs containing `_target_` keys.

    - dicts with `_target_` become calls: target(**children, **kwargs)
    - `_partial_: true` returns functools.partial instead of calling
    - lists/dicts recurse; everything else passes through.
    """
    import functools

    if isinstance(cfg, dict):
        if "_target_" in cfg:
            target = _import_target(cfg["_target_"])
            partial = bool(cfg.get("_partial_", False))
            built = {
                k: instantiate(v)
                for k, v in cfg.items()
                if k not in ("_target_", "_partial_")
            }
            built.update(kwargs)
            if partial:
                return functools.partial(target, **built)
            return target(**built)
        return Config({k: instantiate(v) for k, v in cfg.items()})
    if isinstance(cfg, (list, tuple)):
        return [instantiate(v) for v in cfg]
    return cfg
