"""Compile economy: persistent XLA compilation cache + `jax.export` AOT store.

Two mechanisms, both wired through the `arch.compile_cache` config block
(docs/DESIGN.md §2.7) and both off by default (zero work, bit-identical):

1. **Persistent compilation cache.** `configure()` points
   `jax_compilation_cache_dir` at a shared directory (with the
   min-entry-size / min-compile-time admission knobs) BEFORE the first
   compile, so every re-run — and every peer host of a multi-host fleet
   launch sharing the directory — pays XLA's multi-minute learner compile
   once instead of N times. Cache hits/misses are observable: jax's
   `/jax/compilation_cache/*` monitoring events are folded into the PR 2
   metrics registry as `stoix_tpu_compile_persistent_cache_events_total
   {event=hit|miss}` and surfaced as first-class `cache_hits` bench payload
   fields. A corrupted cache entry degrades to a recompile, never a crash
   (`jax_raise_persistent_cache_errors` stays False;
   tests/test_compilecache.py pins it).

2. **AOT export of the top-level learn function.** `warmup_with_export`
   extends `utils/jax_utils.aot_warmup`: when `arch.compile_cache.export_dir`
   is set, the serialized `jax.export` artifact (StableHLO + shardings) of
   the jitted+shard_mapped learner is loaded when one exists for the same
   input avals / topology / jax version, else compiled once and serialized
   for peers. The deserialized path trades buffer donation for tracing
   economy (an `Exported.call` cannot donate its operands — documented in
   §2.7), so it is opt-in and separate from the cache dir knob.

Everything here is host-side setup code: nothing in this module is
jit-reachable, and failures downgrade with a logged warning instead of
killing a launch (an AOT store is an optimization, never a correctness
dependency).
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.export as jax_export

from stoix_tpu.observability import get_logger, get_registry

# jax's monitoring event names for the persistent compilation cache
# (stable across the 0.4.x line; unknown names simply never fire).
_EVENT_HITS = "/jax/compilation_cache/cache_hits"
_EVENT_MISSES = "/jax/compilation_cache/cache_misses"

_CACHE_EVENTS_METRIC = "stoix_tpu_compile_persistent_cache_events_total"

_listener_lock = threading.Lock()
_listener_installed = False

EXPORT_SUFFIX = ".jaxexport"


def _cache_counter():
    return get_registry().counter(
        _CACHE_EVENTS_METRIC,
        "Persistent XLA compilation cache events, labelled event=hit|miss",
    )


def install_cache_metrics_listener() -> None:
    """Idempotently fold jax's compilation-cache monitoring events into the
    metrics registry. Installed by `configure()`; safe to call repeatedly
    (and from tests) — only the first call registers."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return

        def _on_event(event: str, **_kwargs: Any) -> None:
            if event == _EVENT_HITS:
                _cache_counter().inc(1.0, {"event": "hit"})
            elif event == _EVENT_MISSES:
                _cache_counter().inc(1.0, {"event": "miss"})

        jax.monitoring.register_event_listener(_on_event)
        _listener_installed = True


def cache_stats() -> Dict[str, int]:
    """Persistent-cache hit/miss totals for this process (registry-backed)."""
    counter = _cache_counter()
    return {
        "hits": int(counter.value({"event": "hit"})),
        "misses": int(counter.value({"event": "miss"})),
    }


def configure_cache(
    cache_dir: str,
    min_entry_size_bytes: int = 0,
    min_compile_time_secs: float = 0.0,
) -> None:
    """Point jax's persistent compilation cache at `cache_dir` with the given
    admission knobs, and start recording hit/miss metrics. Must run before
    the first compile of interest; later compiles in this process all flow
    through the cache."""
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes", int(min_entry_size_bytes)
    )
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", float(min_compile_time_secs)
    )
    # jax latches is-the-cache-used ONCE per process, at its first compile: a
    # single jit executed before this point (an import-time helper, an env
    # probe) would silently disable the cache for the whole run. Reset the
    # latch so it re-evaluates under the directory we just configured.
    from jax.experimental.compilation_cache import compilation_cache

    compilation_cache.reset_cache()
    install_cache_metrics_listener()


def settings_from_config(config: Any) -> Dict[str, Any]:
    """The `arch.compile_cache` block as a plain dict with defaults applied
    (the dict-style read keeps STX009 happy on configs that omit the block)."""
    block = (config.arch.get("compile_cache") or {})
    return {
        "enabled": bool(block.get("enabled", False)),
        "dir": block.get("dir") or os.path.join("checkpoints", "xla_cache"),
        "min_entry_size_bytes": int(block.get("min_entry_size_bytes", 0) or 0),
        "min_compile_time_secs": float(block.get("min_compile_time_secs", 0.0) or 0.0),
        "export_dir": block.get("export_dir"),
    }


def configure(config: Any) -> bool:
    """Wire the persistent cache from `arch.compile_cache`; returns whether it
    was enabled. Runs before any compile in both run entry points
    (systems/runner.py and the Sebulba learner)."""
    settings = settings_from_config(config)
    if not settings["enabled"]:
        return False
    configure_cache(
        settings["dir"],
        min_entry_size_bytes=settings["min_entry_size_bytes"],
        min_compile_time_secs=settings["min_compile_time_secs"],
    )
    get_logger("stoix_tpu.compilecache").info(
        "[compilecache] persistent XLA cache at %s (min entry %d B, min "
        "compile %.1f s)",
        settings["dir"], settings["min_entry_size_bytes"],
        settings["min_compile_time_secs"],
    )
    return True


# ---------------------------------------------------------------------------
# jax.export AOT serialize/load of the top-level learn function
# ---------------------------------------------------------------------------


def _aval_digest(example_args: Tuple[Any, ...]) -> str:
    """Stable digest of the call signature the export is valid for: input
    avals + jax version + backend + device count. Anything that changes the
    compiled program's meaning changes the file name, so a stale artifact is
    simply never loaded (invalidation by construction, docs/DESIGN.md §2.7)."""
    avals = jax.tree.map(
        lambda leaf: str(jax.api_util.shaped_abstractify(leaf)), example_args
    )
    payload = "|".join(
        [
            str(avals),
            jax.__version__,
            jax.default_backend(),
            str(jax.device_count()),
        ]
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def export_artifact_path(export_dir: str, name: str, example_args: Tuple[Any, ...]) -> str:
    digest = _aval_digest(example_args)
    safe_name = "".join(c if (c.isalnum() or c in "-_") else "_" for c in name)
    return os.path.join(export_dir, f"{safe_name}-{digest}{EXPORT_SUFFIX}")


_registered_serializations: set = set()


def register_tree_serialization(tree: Any) -> None:
    """Make every NamedTuple node in `tree` serializable by jax.export.

    Learner states are NamedTuples of NamedTuples (PPOLearnerState,
    ActorCriticParams, optax's ScaleByAdamState, ...) and jax.export refuses
    to serialize unregistered custom pytree types. Registration needs a
    STABLE name — module.qualname is stable across processes of the same
    codebase, which is exactly the export store's compatibility domain (the
    aval digest already pins jax version/backend/topology). Idempotent;
    symmetric for serialize and deserialize, so both paths call it. Custom
    non-NamedTuple pytree nodes (if a system ever carries one) still fail
    registration-free and degrade to compile-from-source with the logged
    warning."""

    def _walk(node: Any) -> None:
        if isinstance(node, tuple) and hasattr(node, "_fields"):
            cls = type(node)
            if cls not in _registered_serializations:
                _registered_serializations.add(cls)
                try:
                    jax_export.register_namedtuple_serialization(
                        cls,
                        serialized_name=f"{cls.__module__}.{cls.__qualname__}",
                    )
                except ValueError:
                    pass  # already registered by an earlier caller/test
            for field in node:
                _walk(field)
        elif isinstance(node, (tuple, list)):
            for item in node:
                _walk(item)
        elif isinstance(node, dict):
            for item in node.values():
                _walk(item)

    _walk(tree)


def _register_signature(jit_fn: Callable, example_args: Tuple[Any, ...]) -> None:
    """Register NamedTuple serialization for the call's INPUT and OUTPUT
    trees (the output — e.g. ExperimentOutput — only exists abstractly, so
    it comes from eval_shape: a trace without the lowering the export store
    exists to skip). Needed symmetrically: serialize records the names,
    deserialize resolves them back to classes."""
    register_tree_serialization(example_args)
    try:
        register_tree_serialization(jax.eval_shape(jit_fn, *example_args))
    except Exception as exc:  # noqa: BLE001 — registration is best-effort; export will report
        get_logger("stoix_tpu.compilecache").warning(
            "[compilecache] could not abstract-trace outputs for serialization "
            "registration (%s: %s)", type(exc).__name__, exc,
        )


def save_exported(jit_fn: Callable, example_args: Tuple[Any, ...], path: str) -> bool:
    """Serialize the jitted callable for `example_args` to `path`; False (with
    a logged warning) when the function or backend is not exportable."""
    log = get_logger("stoix_tpu.compilecache")
    try:
        _register_signature(jit_fn, example_args)
        exported = jax_export.export(jit_fn)(*example_args)
        blob = exported.serialize()
    except Exception as exc:  # noqa: BLE001 — export is an optimization, not a dependency
        log.warning(
            "[compilecache] jax.export serialize failed (%s: %s) — peers will "
            "compile from source", type(exc).__name__, exc,
        )
        return False
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)  # atomic: a concurrent peer never reads a torn file
    log.info("[compilecache] exported learn function -> %s (%d bytes)", path, len(blob))
    return True


def load_exported(path: str) -> Optional[Callable]:
    """Deserialize an exported learn function; None (with a logged warning)
    when missing or unloadable — the caller then compiles from source."""
    log = get_logger("stoix_tpu.compilecache")
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            blob = f.read()
        exported = jax_export.deserialize(blob)
        return exported.call
    except Exception as exc:  # noqa: BLE001 — a stale/corrupt artifact degrades to recompile
        log.warning(
            "[compilecache] could not load AOT export %s (%s: %s) — compiling "
            "from source", path, type(exc).__name__, exc,
        )
        return None


def warmup_with_export(
    jit_fn: Callable,
    example_args: Tuple[Any, ...],
    export_dir: Optional[str],
    name: str,
) -> Tuple[Callable, Dict[str, Any]]:
    """AOT-warm the jitted callable, optionally through the `jax.export`
    store: with `export_dir` set, a matching serialized artifact is loaded
    (skipping trace+lower; the StableHLO→executable compile that remains can
    additionally hit the persistent cache), else the function is compiled and
    serialized for peers. Returns `(callable, info)` with info carrying
    `source` (export|compile), `export_path`, and `compile_s`.

    The exported path does NOT preserve donation (an Exported.call cannot
    donate operands), so it changes memory behavior, never values.
    """
    from stoix_tpu.utils.jax_utils import aot_warmup

    info: Dict[str, Any] = {"source": "compile", "export_path": None}
    start = time.perf_counter()
    if export_dir:
        path = export_artifact_path(export_dir, name, example_args)
        info["export_path"] = path
        if os.path.exists(path):
            # Deserialization resolves the serialized NamedTuple names back
            # to classes, so this process must register them first too.
            _register_signature(jit_fn, example_args)
        loaded = load_exported(path)
        if loaded is not None:
            compiled = aot_warmup(jax.jit(loaded), *example_args)
            info["source"] = "export"
            info["compile_s"] = time.perf_counter() - start
            get_logger("stoix_tpu.compilecache").info(
                "[compilecache] learn function restored from AOT export %s "
                "(%.2fs to executable)", path, info["compile_s"],
            )
            return compiled, info
    compiled = aot_warmup(jit_fn, *example_args)
    info["compile_s"] = time.perf_counter() - start
    if export_dir:
        save_exported(jit_fn, example_args, info["export_path"])
    return compiled, info
