"""Cached URL fetch (reference stoix/utils/download.py:8-41) — used by systems
that ship pretrained artifacts (the reference's disco_rl pulls learned
update-rule weights). Downloads are cached under ~/.cache/stoix_tpu and
re-used; environments without egress simply require the file to be placed in
the cache (or passed via `local_path`) ahead of time.
"""

from __future__ import annotations

import hashlib
import os
import urllib.request
from typing import Optional


def cache_dir() -> str:
    root = os.environ.get(
        "STOIX_TPU_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "stoix_tpu")
    )
    os.makedirs(root, exist_ok=True)
    return root


def cached_download(url: str, filename: Optional[str] = None, local_path: Optional[str] = None) -> str:
    """Returns a local path for `url`, downloading once into the cache.

    `local_path` short-circuits the download (for air-gapped environments).
    """
    if local_path is not None:
        if not os.path.exists(local_path):
            raise FileNotFoundError(f"local_path {local_path} does not exist")
        return local_path

    if filename is None:
        digest = hashlib.sha256(url.encode()).hexdigest()[:16]
        filename = f"{digest}_{os.path.basename(url) or 'artifact'}"
    target = os.path.join(cache_dir(), filename)
    if os.path.exists(target):
        return target

    import tempfile

    # Per-call unique tmp file so concurrent downloaders never interleave
    # writes; os.replace keeps publication atomic.
    fd, tmp = tempfile.mkstemp(dir=cache_dir(), suffix=".part")
    os.close(fd)
    try:
        urllib.request.urlretrieve(url, tmp)
    except Exception as e:
        if os.path.exists(tmp):
            os.remove(tmp)
        raise RuntimeError(
            f"Could not download {url} (no egress?). Place the file at {target} "
            "manually, or pass local_path."
        ) from e
    os.replace(tmp, target)
    return target
