from stoix_tpu.utils.config import Config, compose, default_config_dir, instantiate

__all__ = ["Config", "compose", "default_config_dir", "instantiate"]
