"""Rolling-window wall-clock timers — the Sebulba profiling backbone
(reference stoix/utils/timing_utils.py:8-132)."""

from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator


class TimingTracker:
    def __init__(self, maxlen: int = 10):
        self._maxlen = maxlen
        self._times: Dict[str, deque] = {}

    @contextmanager
    def time(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._times.setdefault(name, deque(maxlen=self._maxlen)).append(
                time.perf_counter() - start
            )

    def mean(self, name: str) -> float:
        times = self._times.get(name)
        return sum(times) / len(times) if times else 0.0

    def latest(self, name: str) -> float:
        times = self._times.get(name)
        return times[-1] if times else 0.0

    def all_means(self, prefix: str = "") -> Dict[str, float]:
        return {f"{prefix}{k}_time": self.mean(k) for k in self._times}
