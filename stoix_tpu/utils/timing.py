"""Rolling-window wall-clock timers — the Sebulba profiling backbone
(reference stoix/utils/timing_utils.py:8-132)."""

from __future__ import annotations

import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, Iterator


class TimingTracker:
    def __init__(self, maxlen: int = 10):
        self._maxlen = maxlen
        self._times: Dict[str, deque] = {}

    @contextmanager
    def time(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        try:
            yield
        finally:
            self._times.setdefault(name, deque(maxlen=self._maxlen)).append(
                time.perf_counter() - start
            )

    def record(self, name: str, seconds: float) -> None:
        """Record an externally measured duration (the serving path measures
        request latency at completion time, not around a with-block)."""
        self._times.setdefault(name, deque(maxlen=self._maxlen)).append(float(seconds))

    def mean(self, name: str) -> float:
        times = self._times.get(name)
        return sum(times) / len(times) if times else 0.0

    def latest(self, name: str) -> float:
        times = self._times.get(name)
        return times[-1] if times else 0.0

    def all_means(self, prefix: str = "") -> Dict[str, float]:
        return {f"{prefix}{k}_time": self.mean(k) for k in self._times}

    def percentiles(self, name: str) -> Dict[str, float]:
        """p50/p95/p99/max over the current rolling window (nearest-rank on
        the sorted window: p50 of a single sample is that sample). p99 exists
        for the serving SLOs (docs/DESIGN.md §2.8) — tail latency is the
        metric a latency SLO is written against. Empty window -> {} so
        callers can `.update()` unconditionally."""
        times = self._times.get(name)
        if not times:
            return {}
        ordered = sorted(times)
        n = len(ordered)

        def rank(q: float) -> float:
            return ordered[min(n - 1, max(0, int(q * n + 0.5) - 1))]

        return {
            "p50": rank(0.50),
            "p95": rank(0.95),
            "p99": rank(0.99),
            "max": ordered[-1],
        }

    def all_percentiles(self, prefix: str = "") -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name in self._times:
            for stat, value in self.percentiles(name).items():
                out[f"{prefix}{name}_{stat}"] = value
        return out
