"""Multi-sink experiment logger (reference stoix/utils/logger.py:28-613).

StoixLogger equivalent: thread-safe fan-out to Console / JSON (marl-eval
layout) / TensorBoard sinks, toggled by config. Events ACT/TRAIN/EVAL/ABSOLUTE/
MISC; non-TRAIN metrics get mean/std/min/max description; optional solve-rate
metric from `env.solved_return_threshold`. W&B/Neptune are not bundled in this
environment — the sink interface below is where they would plug in.
"""

from __future__ import annotations

import enum
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np


class LogEvent(enum.Enum):
    ACT = "actor"
    TRAIN = "trainer"
    EVAL = "evaluator"
    ABSOLUTE = "absolute"
    MISC = "misc"


def describe(x: Any) -> Dict[str, float]:
    arr = np.asarray(x, dtype=np.float32).reshape(-1)
    # Mask non-finite entries: one NaN/inf episode metric (a diverged env,
    # an inf-return overflow) must not poison all four summary stats.
    finite = arr[np.isfinite(arr)]
    if finite.size == 0:
        return (
            {}
            if arr.size == 0
            else {"non_finite_count": float(arr.size)}
        )
    stats = {
        "mean": float(finite.mean()),
        "std": float(finite.std()),
        "min": float(finite.min()),
        "max": float(finite.max()),
    }
    if finite.size != arr.size:
        stats["non_finite_count"] = float(arr.size - finite.size)
    return stats


class BaseSink:
    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: LogEvent) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ConsoleSink(BaseSink):
    _COLOURS = {
        LogEvent.ACT: "\033[95m",
        LogEvent.TRAIN: "\033[94m",
        LogEvent.EVAL: "\033[92m",
        LogEvent.ABSOLUTE: "\033[93m",
        LogEvent.MISC: "\033[96m",
    }

    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: LogEvent) -> None:
        colour = self._COLOURS.get(event, "")
        parts = " | ".join(
            f"{k.replace('_', ' ').title()}: {v:.3f}" if isinstance(v, float) else f"{k}: {v}"
            for k, v in sorted(metrics.items())
        )
        print(f"{colour}[{event.value.upper()} t={t}]\033[0m {parts}", flush=True)


class JsonSink(BaseSink):
    """marl-eval-compatible JSON logging (reference logger.py:325-386): nested
    {env}/{task}/{system}/seed_{n} with per-eval-step metric lists, restricted
    to episode_return / solve-rate / steps_per_second on EVAL/ABSOLUTE events.
    """

    def __init__(
        self,
        path: str,
        env_name: str,
        task_name: str,
        system_name: str,
        seed: int,
    ):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._path = path
        self._keys = (env_name, task_name, system_name, f"seed_{seed}")
        self._data: Dict[str, Any] = {}
        node = self._data
        for k in self._keys[:-1]:
            node = node.setdefault(k, {})
        node[self._keys[-1]] = {}

    def _leaf(self) -> Dict[str, Any]:
        node = self._data
        for k in self._keys[:-1]:
            node = node[k]
        return node[self._keys[-1]]

    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: LogEvent) -> None:
        if event not in (LogEvent.EVAL, LogEvent.ABSOLUTE):
            return
        leaf = self._leaf()
        step_key = "absolute_metrics" if event == LogEvent.ABSOLUTE else f"step_{t_eval}"
        entry = leaf.setdefault(step_key, {"step_count": t})
        for k, v in metrics.items():
            if k.startswith("episode_return") or k in ("solve_rate", "steps_per_second"):
                entry.setdefault(k, []).append(float(v))
        with open(self._path, "w") as f:
            json.dump(self._data, f, indent=2)


class TensorboardSink(BaseSink):
    def __init__(self, logdir: str):
        from torch.utils.tensorboard import SummaryWriter  # torch-cpu is bundled

        self._writer = SummaryWriter(log_dir=logdir)

    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: LogEvent) -> None:
        for k, v in metrics.items():
            if isinstance(v, (int, float, np.floating, np.integer)):
                self._writer.add_scalar(f"{event.value}/{k}", float(v), t)

    def close(self) -> None:
        self._writer.close()


class _OfflineRunDir:
    """Shared offline-run-directory machinery for the wandb/neptune sinks'
    package-absent fallbacks: a run directory with a metadata JSON and an
    append-mode history.jsonl (append so run-id resumes continue the file)."""

    def __init__(
        self,
        base: str,
        metadata: Dict[str, Any],
        metadata_name: str,
        history_name: str,
        files_subdir: Optional[str] = None,
    ):
        self.dir = base
        self.files_dir = os.path.join(base, files_subdir) if files_subdir else base
        os.makedirs(self.files_dir, exist_ok=True)
        with open(os.path.join(self.files_dir, metadata_name), "w") as f:
            json.dump(metadata, f, indent=2)
        self._history = open(os.path.join(base, history_name), "a")

    def write_row(self, row: Dict[str, Any]) -> None:
        self._history.write(json.dumps(row) + "\n")
        self._history.flush()

    def close(self) -> None:
        self._history.close()


class WandbSink(BaseSink):
    """Weights & Biases sink (reference logger.py:188-258).

    With the `wandb` package installed, logs through a real `wandb.init`
    run — `mode="offline"` by default so egress-blocked machines record runs
    syncable later with `wandb sync`. Without the package (this sandbox),
    writes a wandb-style offline run directory instead:

        <dir>/offline-run-<stamp>/files/wandb-metadata.json   (run metadata)
        <dir>/offline-run-<stamp>/files/config.yaml           (run config)
        <dir>/offline-run-<stamp>/files/wandb-summary.json    (latest values)
        <dir>/offline-run-<stamp>/wandb-history.jsonl         (per-step rows,
                                                               _step/_runtime/
                                                               _timestamp keys)

    The fallback keeps the metric layout identical (event-prefixed keys,
    history rows keyed by `_step`), so dashboards or scripts written against
    the W&B export format read either source.
    """

    def __init__(
        self,
        run_dir: str,
        project: str = "stoix_tpu",
        mode: str = "offline",
        config_dict: Optional[Dict[str, Any]] = None,
        run_id: Optional[str] = None,
        **init_kwargs: Any,
    ):
        self._start = time.time()
        self._run = None
        self._offline: Optional[_OfflineRunDir] = None
        self._summary: Dict[str, Any] = {}
        # run_id resume (reference logger.py:501-504): resume="allow" attaches
        # to the existing W&B run — the multi-process / checkpoint-resume flow.
        if run_id is not None:
            init_kwargs.update(id=run_id, resume="allow")
        try:
            import wandb

            self._run = wandb.init(
                project=project, dir=run_dir, mode=mode, config=config_dict, **init_kwargs
            )
        except ImportError:
            stamp = time.strftime("%Y%m%d_%H%M%S")
            self._offline = _OfflineRunDir(
                base=os.path.join(run_dir, f"offline-run-{stamp}"),
                metadata={
                    "project": project,
                    "mode": mode,
                    "startedAt": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    "writer": "stoix_tpu.WandbSink (wandb package not installed)",
                },
                metadata_name="wandb-metadata.json",
                history_name="wandb-history.jsonl",
                files_subdir="files",
            )
            if config_dict is not None:
                try:
                    import yaml

                    with open(
                        os.path.join(self._offline.files_dir, "config.yaml"), "w"
                    ) as f:
                        yaml.safe_dump(config_dict, f)
                except Exception:  # noqa: BLE001 — config snapshot is best-effort
                    pass

    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: LogEvent) -> None:
        row = {f"{event.value}/{k}": v for k, v in metrics.items()}
        if self._run is not None:
            self._run.log(row, step=t)
            return
        now = time.time()
        row.update({"_step": t, "_runtime": now - self._start, "_timestamp": now})
        self._offline.write_row(row)
        self._summary.update(row)
        with open(os.path.join(self._offline.files_dir, "wandb-summary.json"), "w") as f:
            json.dump(self._summary, f)

    def close(self) -> None:
        if self._run is not None:
            self._run.finish()
        else:
            self._offline.close()


class NeptuneSink(BaseSink):
    """neptune.ai sink (reference logger.py:222-299 NeptuneLogger).

    With the `neptune` package installed, logs through a real
    `neptune.init_run` — `run_id` resumes an existing run via `with_id`
    (reference :257-258, the multi-process / checkpoint-resume flow), sync
    mode under Sebulba because async neptune logging deadlocks with the
    thread pools (reference :255). Without the package (this sandbox),
    writes a neptune-style offline run directory instead (shared
    _OfflineRunDir machinery with the wandb fallback):

        <dir>/neptune-run-<stamp>/run-metadata.json   (project/tags/mode)
        <dir>/neptune-run-<stamp>/history.jsonl       (rows: {key, value, step})

    keeping the event-prefixed key layout identical so downstream readers
    see the same channel names either way.
    """

    def __init__(
        self,
        run_dir: str,
        project: str = "stoix_tpu",
        tag: Optional[list] = None,
        group_tag: Optional[list] = None,
        detailed_logging: bool = False,
        architecture_name: str = "anakin",
        run_id: Optional[str] = None,
        **init_kwargs: Any,
    ):
        self._detailed = bool(detailed_logging)
        self._run = None
        self._offline: Optional[_OfflineRunDir] = None
        # Async logging deadlocks under Sebulba's thread pools (reference
        # logger.py:255): sync there, async in the single-threaded Anakin loop.
        mode = "async" if architecture_name == "anakin" else "sync"
        try:
            import neptune

            if run_id is not None:
                self._run = neptune.init_run(with_id=run_id, project=project, mode=mode)
            else:
                self._run = neptune.init_run(
                    project=project, tags=list(tag or []), mode=mode, **init_kwargs
                )
                self._run["sys/group_tags"].add(list(group_tag or []))
        except ImportError:
            stamp = time.strftime("%Y%m%d_%H%M%S")
            # run_id pins the directory name so a resume appends to the same
            # history file.
            self._offline = _OfflineRunDir(
                base=os.path.join(run_dir, f"neptune-run-{run_id or stamp}"),
                metadata={
                    "project": project,
                    "mode": mode,
                    "tags": list(tag or []),
                    "group_tags": list(group_tag or []),
                    "resumed_run_id": run_id,
                    "startedAt": time.strftime("%Y-%m-%dT%H:%M:%S"),
                    "writer": "stoix_tpu.NeptuneSink (neptune package not installed)",
                },
                metadata_name="run-metadata.json",
                history_name="history.jsonl",
            )

    def _is_main_metric(self, key: str) -> bool:
        # Mean-of-list metrics ('.../mean') and scalar metrics; everything
        # else (std/min/max) only under detailed_logging (reference :272-276).
        return "/" not in key or key.endswith("/mean")

    def write(self, metrics: Dict[str, float], t: int, t_eval: int, event: LogEvent) -> None:
        for k, v in metrics.items():
            if not self._detailed and not self._is_main_metric(k):
                continue
            if not isinstance(v, (int, float, np.floating, np.integer)):
                continue
            if self._run is not None:
                self._run[f"{event.value}/{k}"].log(float(v), step=t)
            else:
                self._offline.write_row(
                    {"key": f"{event.value}/{k}", "value": float(v), "step": t}
                )

    def close(self) -> None:
        if self._run is not None:
            self._run.stop()
        else:
            self._offline.close()


class StoixLogger:
    """Thread-safe fan-out logger. `log` accepts raw (possibly array-valued)
    metrics; non-TRAIN events are described (mean/std/min/max)."""

    def __init__(self, config: Any):
        self._lock = threading.Lock()
        self._sinks: List[BaseSink] = []
        self._solve_threshold: Optional[float] = None
        logger_cfg = config.logger
        env_name = config.env.env_name
        task_name = config.env.scenario.task_name
        system_name = logger_cfg.get("system_name") or "system"
        seed = int(config.arch.seed)
        stamp = time.strftime("%Y%m%d%H%M%S")
        exp_dir = os.path.join(
            logger_cfg.base_exp_path, f"{system_name}", f"{task_name}", f"seed_{seed}_{stamp}"
        )
        self.exp_dir = exp_dir

        if logger_cfg.get("use_console", True):
            self._sinks.append(ConsoleSink())
        if logger_cfg.get("use_json", False):
            json_path = (logger_cfg.get("kwargs") or {}).get("json_path") or os.path.join(
                exp_dir, "metrics.json"
            )
            self._sinks.append(JsonSink(json_path, env_name, task_name, system_name, seed))
        if logger_cfg.get("use_tb", False):
            self._sinks.append(TensorboardSink(os.path.join(exp_dir, "tb")))
        if logger_cfg.get("use_wandb", False):
            kwargs = dict(logger_cfg.get("wandb_kwargs") or {})
            kwargs.setdefault("project", "stoix_tpu")
            cfg_snapshot = config.to_dict() if hasattr(config, "to_dict") else None
            self._sinks.append(
                WandbSink(os.path.join(exp_dir, "wandb"), config_dict=cfg_snapshot, **kwargs)
            )
        if logger_cfg.get("use_neptune", False):
            kwargs = dict(logger_cfg.get("neptune_kwargs") or {})
            kwargs.setdefault("project", "stoix_tpu")
            kwargs.setdefault("tag", (logger_cfg.get("kwargs") or {}).get("neptune_tag") or [])
            kwargs.setdefault(
                "architecture_name", getattr(config.arch, "architecture_name", "anakin")
            )
            self._sinks.append(NeptuneSink(os.path.join(exp_dir, "neptune"), **kwargs))

        # Telemetry (observability package): configure is the single switch —
        # disabled (default) records nothing and starts no threads. Enabled,
        # a TelemetrySink fans registry snapshots into Prometheus/JSONL files
        # and exports the span trace on close (docs/DESIGN.md §2.2).
        from stoix_tpu import observability

        telemetry_cfg = logger_cfg.get("telemetry") or {}
        if observability.configure(telemetry_cfg):
            from stoix_tpu.observability.sink import TelemetrySink

            telemetry_dir = telemetry_cfg.get("dir") or os.path.join(exp_dir, "telemetry")
            self._sinks.append(
                TelemetrySink(
                    telemetry_dir,
                    min_write_interval_s=float(
                        telemetry_cfg.get("min_write_interval_s", 0.0) or 0.0
                    ),
                )
            )

        self._solve_threshold = config.env.get("solved_return_threshold")

    def log(self, metrics: Dict[str, Any], t: int, t_eval: int, event: LogEvent) -> None:
        processed: Dict[str, float] = {}
        for k, v in metrics.items():
            arr = np.asarray(v)
            if arr.size == 0:
                continue
            if event == LogEvent.TRAIN or arr.size == 1:
                processed[k] = float(arr.mean())
            else:
                for stat, val in describe(arr).items():
                    processed[f"{k}/{stat}"] = val

        # Solve-rate custom metric (reference logger.py:36-74).
        if (
            self._solve_threshold is not None
            and event in (LogEvent.EVAL, LogEvent.ABSOLUTE)
            and "episode_return" in metrics
        ):
            returns = np.asarray(metrics["episode_return"]).reshape(-1)
            if returns.size:
                processed["solve_rate"] = float(
                    (returns >= self._solve_threshold).mean() * 100.0
                )

        with self._lock:
            for sink in self._sinks:
                sink.write(processed, t, t_eval, event)

    def close(self) -> None:
        with self._lock:
            for sink in self._sinks:
                sink.close()
