"""Small JAX helpers (reference stoix/utils/jax_utils.py:12-115)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def scale_gradient(x: jax.Array, scale: float) -> jax.Array:
    """Identity forward, gradient scaled by `scale` on the way back."""
    return x * scale + jax.lax.stop_gradient(x) * (1.0 - scale)


def count_parameters(params: Any) -> int:
    return int(sum(jnp.size(leaf) for leaf in jax.tree.leaves(params)))


def merge_leading_dims(x: jax.Array, num_dims: int) -> jax.Array:
    return x.reshape((-1,) + x.shape[num_dims:])


def tree_merge_leading_dims(tree: Any, num_dims: int) -> Any:
    return jax.tree.map(lambda x: merge_leading_dims(x, num_dims), tree)


def select_pytree(pred: jax.Array, on_true: Any, on_false: Any) -> Any:
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), on_true, on_false)


def aot_compile(fn: Any, *example_args: Any) -> Any:
    """Ahead-of-time trace/lower/compile with a FLOPs estimate printed
    (reference jax_utils.py:68-115)."""
    lowered = jax.jit(fn).lower(*example_args)
    compiled = lowered.compile()
    try:
        cost = compiled.cost_analysis()
        flops = cost.get("flops") if isinstance(cost, dict) else cost[0].get("flops")
        if flops:
            from stoix_tpu.observability import get_logger

            get_logger("stoix_tpu.aot").info("[aot] estimated FLOPs/call: %.3e", flops)
    except Exception:  # noqa: STX003 — FLOPs estimate is best-effort telemetry
        pass
    return compiled


def aot_warmup(jit_fn: Any, *example_args: Any) -> Any:
    """AOT-compile an ALREADY-jitted callable for the given example arguments
    and return the compiled executable; the jitted fn itself is returned when
    AOT lowering is unsupported (non-jitted wrappers, exotic backends), in
    which case compilation happens on the first call instead.

    Donation declared on the jit (donate_argnums) is preserved by the compiled
    executable. The Anakin runner uses this to pay the learner's XLA compile
    BEFORE the timed host loop, so the first eval window's steps_per_second is
    a real throughput number rather than compile time (the compile used to
    pollute it, runner.py)."""
    try:
        return jit_fn.lower(*example_args).compile()
    except Exception:  # noqa: BLE001 — any lowering failure degrades gracefully
        return jit_fn
