"""Network heads: map torso embeddings to action distributions / value outputs
(reference stoix/networks/heads.py:30-339). Heads return first-party
distributions from stoix_tpu.ops.distributions so acting code is uniform.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks import torso as torso_lib
from stoix_tpu.ops import distributions as dists

_ORTHO_SMALL = nn.initializers.orthogonal(0.01)
_ORTHO_ONE = nn.initializers.orthogonal(1.0)


class CategoricalHead(nn.Module):
    """Discrete policy head; applies the observation's action mask if given."""

    num_actions: int

    @nn.compact
    def __call__(self, embedding: jax.Array, action_mask: Optional[jax.Array] = None) -> dists.Categorical:
        logits = nn.Dense(self.num_actions, kernel_init=_ORTHO_SMALL)(embedding)
        return dists.Categorical(logits, mask=action_mask)


class NormalAffineTanhDistributionHead(nn.Module):
    """Squashed-Gaussian policy on [minimum, maximum] (SAC-style)."""

    action_dim: int
    minimum: float = -1.0
    maximum: float = 1.0
    min_scale: float = 1e-3

    @nn.compact
    def __call__(self, embedding: jax.Array) -> dists.Independent:
        loc = nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding)
        scale = (
            jax.nn.softplus(nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding))
            + self.min_scale
        )
        return dists.Independent(
            dists.TanhNormal(loc, scale, self.minimum, self.maximum), reinterpreted_batch_ndims=1
        )


class BetaDistributionHead(nn.Module):
    """Beta policy on [minimum, maximum]."""

    action_dim: int
    minimum: float = -1.0
    maximum: float = 1.0

    @nn.compact
    def __call__(self, embedding: jax.Array) -> dists.AffineBeta:
        # softplus(+1) keeps alpha, beta > 1 (unimodal).
        alpha = jax.nn.softplus(nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding)) + 1.0
        beta = jax.nn.softplus(nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding)) + 1.0
        return dists.AffineBeta(alpha, beta, self.minimum, self.maximum)


class MultivariateNormalDiagHead(nn.Module):
    """Unsquashed diagonal Gaussian (MPO-style, KL-friendly)."""

    action_dim: int
    init_scale: float = 0.3
    min_scale: float = 1e-6

    @nn.compact
    def __call__(self, embedding: jax.Array) -> dists.MultivariateNormalDiag:
        loc = nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding)
        raw_scale = nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding)
        scale = jax.nn.softplus(raw_scale) * self.init_scale / jax.nn.softplus(jnp.zeros(()))
        return dists.MultivariateNormalDiag(loc, scale + self.min_scale)


class DeterministicHead(nn.Module):
    """Deterministic policy (DDPG/TD3); output bounded by tanh to [min, max]."""

    action_dim: int
    minimum: float = -1.0
    maximum: float = 1.0

    @nn.compact
    def __call__(self, embedding: jax.Array) -> dists.Deterministic:
        x = nn.Dense(self.action_dim, kernel_init=_ORTHO_SMALL)(embedding)
        half_width = (self.maximum - self.minimum) / 2.0
        mid = (self.maximum + self.minimum) / 2.0
        return dists.Deterministic(jnp.tanh(x) * half_width + mid)


class ScalarCriticHead(nn.Module):
    @nn.compact
    def __call__(self, embedding: jax.Array) -> jax.Array:
        return nn.Dense(1, kernel_init=_ORTHO_ONE)(embedding)[..., 0]


class CategoricalCriticHead(nn.Module):
    """Distributional critic over a fixed real support (601 atoms by default,
    reference heads.py:137-158)."""

    num_atoms: int = 601
    vmin: float = -300.0
    vmax: float = 300.0

    @nn.compact
    def __call__(self, embedding: jax.Array) -> dists.DiscreteValued:
        logits = nn.Dense(self.num_atoms, kernel_init=_ORTHO_ONE)(embedding)
        values = jnp.linspace(self.vmin, self.vmax, self.num_atoms)
        return dists.DiscreteValued(logits, values)


class DiscreteQNetworkHead(nn.Module):
    """Q-values head returning an EpsilonGreedy distribution so value-based
    acting composes like policy-based acting (reference heads.py:202-217)."""

    action_dim: int
    epsilon: float = 0.1

    @nn.compact
    def __call__(
        self,
        embedding: jax.Array,
        epsilon: Optional[jax.Array] = None,
        action_mask: Optional[jax.Array] = None,
    ) -> dists.EpsilonGreedy:
        q_values = nn.Dense(self.action_dim, kernel_init=_ORTHO_ONE)(embedding)
        eps = self.epsilon if epsilon is None else epsilon
        return dists.EpsilonGreedy(q_values, eps, mask=action_mask)


class PolicyValueHead(nn.Module):
    """Shared-torso policy + scalar value (IMPALA shared torso, AZ/MZ prediction)."""

    action_head: nn.Module
    critic_head: nn.Module

    @nn.compact
    def __call__(self, embedding: jax.Array, *args, **kwargs) -> Tuple[dists.Distribution, jax.Array]:
        return self.action_head(embedding, *args, **kwargs), self.critic_head(embedding)


class DistributionalDiscreteQNetwork(nn.Module):
    """C51 head: per-action atom logits + fixed support (reference heads.py:235-258).

    Returns (eps_greedy_dist_over_mean_q, atom_logits [..., A, M], atoms [M]).
    """

    action_dim: int
    num_atoms: int = 51
    vmin: float = -10.0
    vmax: float = 10.0
    epsilon: float = 0.1

    @nn.compact
    def __call__(
        self,
        embedding: jax.Array,
        epsilon: Optional[jax.Array] = None,
        action_mask: Optional[jax.Array] = None,
    ) -> Tuple[dists.EpsilonGreedy, jax.Array, jax.Array]:
        atoms = jnp.linspace(self.vmin, self.vmax, self.num_atoms)
        logits = nn.Dense(self.action_dim * self.num_atoms, kernel_init=_ORTHO_ONE)(embedding)
        logits = logits.reshape(embedding.shape[:-1] + (self.action_dim, self.num_atoms))
        q_values = jnp.sum(jax.nn.softmax(logits, axis=-1) * atoms, axis=-1)
        eps = self.epsilon if epsilon is None else epsilon
        return dists.EpsilonGreedy(q_values, eps, mask=action_mask), logits, atoms


class DistributionalContinuousQNetwork(nn.Module):
    """D4PG critic: categorical Q-distribution over a fixed support."""

    num_atoms: int = 51
    vmin: float = -10.0
    vmax: float = 10.0

    @nn.compact
    def __call__(self, embedding: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        atoms = jnp.linspace(self.vmin, self.vmax, self.num_atoms)
        logits = nn.Dense(self.num_atoms, kernel_init=_ORTHO_ONE)(embedding)
        q_value = jnp.sum(jax.nn.softmax(logits, axis=-1) * atoms, axis=-1)
        return q_value, logits, atoms


class QuantileDiscreteQNetwork(nn.Module):
    """QR-DQN head: per-action quantile estimates (reference heads.py:277-293).

    Returns (eps_greedy_over_mean_q, quantiles [..., N, A], taus [..., N]).
    """

    action_dim: int
    num_quantiles: int = 51
    epsilon: float = 0.1

    @nn.compact
    def __call__(
        self,
        embedding: jax.Array,
        epsilon: Optional[jax.Array] = None,
        action_mask: Optional[jax.Array] = None,
    ) -> Tuple[dists.EpsilonGreedy, jax.Array, jax.Array]:
        q_dist = nn.Dense(self.action_dim * self.num_quantiles, kernel_init=_ORTHO_ONE)(embedding)
        q_dist = q_dist.reshape(embedding.shape[:-1] + (self.num_quantiles, self.action_dim))
        q_values = jnp.mean(q_dist, axis=-2)
        tau = (jnp.arange(self.num_quantiles) + 0.5) / self.num_quantiles
        tau = jnp.broadcast_to(tau, embedding.shape[:-1] + (self.num_quantiles,))
        eps = self.epsilon if epsilon is None else epsilon
        return dists.EpsilonGreedy(q_values, eps, mask=action_mask), q_dist, tau


class LinearHead(nn.Module):
    """Raw linear projection (reward/logit heads in world models)."""

    output_dim: int

    @nn.compact
    def __call__(self, embedding: jax.Array) -> jax.Array:
        out = nn.Dense(self.output_dim, kernel_init=_ORTHO_ONE)(embedding)
        return out[..., 0] if self.output_dim == 1 else out


class MLPLogitsHead(nn.Module):
    """MLP torso + raw logits projection — MuZero's 601-atom value/reward
    heads over a transformed support (decoded via ops.value_transforms.
    muzero_pair, never softmaxed here)."""

    num_outputs: int
    hidden_sizes: tuple = (64,)

    @nn.compact
    def __call__(self, embedding: jax.Array) -> jax.Array:
        x = torso_lib.MLPTorso(tuple(self.hidden_sizes))(embedding)
        return nn.Dense(self.num_outputs)(x)


class MultiDiscreteHead(nn.Module):
    """Factorized categorical policy over multiple discrete dims."""

    num_values: Sequence[int]

    @nn.compact
    def __call__(self, embedding: jax.Array) -> dists.MultiDiscrete:
        flat = nn.Dense(int(sum(self.num_values)), kernel_init=_ORTHO_SMALL)(embedding)
        return dists.MultiDiscrete(flat, self.num_values)
