"""Registries mapping config strings to activation / RNN cell constructors
(reference stoix/networks/utils.py:7-37)."""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax

ACTIVATIONS = {
    "relu": nn.relu,
    "tanh": nn.tanh,
    "silu": nn.silu,
    "swish": nn.silu,
    "elu": nn.elu,
    "gelu": nn.gelu,
    "sigmoid": nn.sigmoid,
    "softplus": nn.softplus,
    "leaky_relu": nn.leaky_relu,
    "identity": lambda x: x,
    "none": lambda x: x,
    "normalise": nn.standardize,
}


def parse_activation_fn(name: str) -> Callable[[jax.Array], jax.Array]:
    if callable(name):
        return name
    if name not in ACTIVATIONS:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[name]


RNN_CELLS = {
    "lstm": nn.LSTMCell,
    "optimised_lstm": nn.OptimizedLSTMCell,
    "gru": nn.GRUCell,
    "mgu": nn.MGUCell,
    "simple": nn.SimpleCell,
}


def parse_rnn_cell(name: str) -> Callable:
    if name not in RNN_CELLS:
        raise ValueError(f"Unknown RNN cell '{name}'. Known: {sorted(RNN_CELLS)}")
    return RNN_CELLS[name]
