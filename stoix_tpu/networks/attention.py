"""Attention networks: multi-head self-attention + a transformer torso.

The reference's network zoo has no attention ("no transformer in the network
zoo", SURVEY.md §5 long-context); sequence memory is RNN-only. The TPU build
adds a causal transformer torso as a first-class sequence model: MXU-friendly
batched matmuls end to end, usable anywhere the recurrent torsos are (time-
major stored-sequence learners like rec_r2d2/rec_ppo consume [B, T, ...]
windows), and wired for sequence parallelism — `attention_fn` accepts the
ring-attention primitive (stoix_tpu/ops/ring_attention.py) so the SAME module
runs single-device (full attention) or with the time axis sharded over a mesh
ring (shard_map + ppermute).

Pre-LN blocks (the stable variant for RL-scale training), learned positional
embeddings, causal masking by default.
"""

from __future__ import annotations

from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.ops import best_attention

AttentionFn = Callable[..., jax.Array]  # (q, k, v, causal=...) -> out


class MultiHeadSelfAttention(nn.Module):
    num_heads: int = 4
    head_dim: int = 32
    causal: bool = True
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        # x: [B, T, F] -> [B, T, H*D]
        b, t, _ = x.shape
        proj = nn.DenseGeneral(
            (3, self.num_heads, self.head_dim),
            kernel_init=nn.initializers.orthogonal(1.0),
            name="qkv",
        )(x)  # [B, T, 3, H, D]
        q, k, v = proj[:, :, 0], proj[:, :, 1], proj[:, :, 2]
        # Default dispatch: the Pallas flash kernel on TPU (fused online
        # softmax, no [S, S] score matrix in HBM — 3x the XLA path at S=4k),
        # pure-JAX full attention elsewhere.
        attend = self.attention_fn or best_attention
        out = attend(q, k, v, causal=self.causal)  # [B, T, H, D]
        out = out.reshape(b, t, self.num_heads * self.head_dim)
        return nn.Dense(
            self.num_heads * self.head_dim,
            kernel_init=nn.initializers.orthogonal(1.0),
            name="out",
        )(out)


class TransformerBlock(nn.Module):
    num_heads: int = 4
    head_dim: int = 32
    ffn_dim: int = 256
    causal: bool = True
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        width = self.num_heads * self.head_dim
        attn = MultiHeadSelfAttention(
            num_heads=self.num_heads,
            head_dim=self.head_dim,
            causal=self.causal,
            attention_fn=self.attention_fn,
        )(nn.LayerNorm()(x))
        x = x + attn
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.ffn_dim, kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)))(h)
        h = nn.silu(h)
        h = nn.Dense(width, kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)))(h)
        return x + h


class TransformerTorso(nn.Module):
    """Causal transformer over the time axis: [B, T, F] -> [B, T, width].

    Drop-in sequence torso for stored-sequence learners; set
    `attention_fn=partial(ring_attention, axis_name=...)` inside a shard_map
    to shard T over a mesh ring for long-context training.
    """

    num_layers: int = 2
    num_heads: int = 4
    head_dim: int = 32
    ffn_dim: int = 256
    max_timesteps: int = 512
    causal: bool = True
    attention_fn: Optional[AttentionFn] = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, _ = x.shape
        width = self.num_heads * self.head_dim
        x = nn.Dense(width, kernel_init=nn.initializers.orthogonal(jnp.sqrt(2)))(x)
        pos = self.param(
            "positional_embedding",
            nn.initializers.normal(0.02),
            (self.max_timesteps, width),
        )
        x = x + pos[:t][None]
        for i in range(self.num_layers):
            x = TransformerBlock(
                num_heads=self.num_heads,
                head_dim=self.head_dim,
                ffn_dim=self.ffn_dim,
                causal=self.causal,
                attention_fn=self.attention_fn,
                name=f"block_{i}",
            )(x)
        return nn.LayerNorm()(x)
