"""World model for MuZero-family systems
(reference stoix/networks/model_based.py:15-129).

RewardBasedWorldModel: obs encoder -> hidden state; stacked-RNN dynamics over
embedded actions with residual next-state and min-max hidden normalization;
reward head on the dynamics output. Hidden RNN carries are packed into a flat
vector between search steps so the MCTS tree stores one array per node.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks.layers import StackedRNN
from stoix_tpu.networks.postprocessors import min_max_normalize


class RewardBasedWorldModel(nn.Module):
    obs_encoder: nn.Module  # torso over the observation input
    reward_head: nn.Module  # embedding -> scalar reward
    action_embedder: nn.Module  # action array -> embedding
    hidden_size: int = 256
    num_rnn_layers: int = 2
    rnn_cell_type: str = "lstm"
    normalize_hidden: bool = True

    def setup(self) -> None:
        self.dynamics = StackedRNN(self.hidden_size, self.num_rnn_layers, self.rnn_cell_type)
        self.obs_to_hidden = nn.Dense(self.hidden_size)

    # --- flat <-> structured RNN-state packing (reference model_based.py:49-75)
    def _flat_dim(self) -> int:
        # LSTM carries (c, h); GRU and simple carry one array.
        per_layer = 2 if self.rnn_cell_type in ("lstm", "optimised_lstm") else 1
        return self.num_rnn_layers * per_layer * self.hidden_size

    def pack_state(self, states: Tuple[Any, ...]) -> jax.Array:
        leaves = jax.tree.leaves(states)
        return jnp.concatenate([leaf for leaf in leaves], axis=-1)

    def unpack_state(self, flat: jax.Array) -> Tuple[Any, ...]:
        per_layer = 2 if self.rnn_cell_type in ("lstm", "optimised_lstm") else 1
        chunks = jnp.split(flat, self.num_rnn_layers * per_layer, axis=-1)
        states = []
        for i in range(self.num_rnn_layers):
            if per_layer == 2:
                states.append((chunks[2 * i], chunks[2 * i + 1]))
            else:
                states.append(chunks[i])
        return tuple(states)

    def initial_state(self, observation: Any) -> jax.Array:
        """Encode an observation into the flat world-model hidden state."""
        embedding = self.obs_encoder(observation)
        batch_shape = embedding.shape[:-1]
        carry = self.dynamics.initialize_carry(jax.random.PRNGKey(0), batch_shape + (self.hidden_size,))
        # Seed every layer's hidden output with the embedding projection.
        proj = self.obs_to_hidden(embedding)
        if self.rnn_cell_type in ("lstm", "optimised_lstm"):
            carry = tuple((c, proj) for (c, _h) in carry)
        else:
            carry = tuple(proj for _ in carry)
        flat = self.pack_state(carry)
        return min_max_normalize(flat) if self.normalize_hidden else flat

    def step(self, flat_state: jax.Array, action: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """One latent dynamics step: returns (next_flat_state, reward)."""
        states = self.unpack_state(flat_state)
        a_emb = self.action_embedder(action)
        new_states, out = self.dynamics(states, a_emb)
        new_flat = self.pack_state(new_states)
        # Residual connection then optional min-max normalization
        # (reference model_based.py:91-97) keeps latent scale bounded.
        new_flat = new_flat + flat_state
        if self.normalize_hidden:
            new_flat = min_max_normalize(new_flat)
        reward = self.reward_head(out)
        return new_flat, reward

    def __call__(self, observation: Any, action: jax.Array):
        """Init-everything path for nn.init: touch all submodules."""
        flat = self.initial_state(observation)
        return self.step(flat, action)
