"""Residual torsos (reference stoix/networks/resnet.py:48-188): IMPALA-style
visual ResNet and MLP ResNet, with selectable downsampling strategies."""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks.utils import parse_activation_fn


class ResidualBlock(nn.Module):
    channels: int
    activation: str = "relu"
    use_layer_norm: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = parse_activation_fn(self.activation)
        y = x
        for _ in range(2):
            if self.use_layer_norm:
                y = nn.LayerNorm(use_scale=True)(y)
            y = act(y)
            y = nn.Conv(self.channels, kernel_size=(3, 3), strides=(1, 1))(y)
        return x + y


class DownsamplingStrategy:
    CONV_MAX = "conv+max"  # IMPALA: stride-1 conv then 3x3 max-pool stride 2
    LAYERNORM_RELU_CONV = "layernorm+relu+conv"  # MuZero-style strided conv
    CONV = "conv"


def _downsample(x: jax.Array, channels: int, strategy: str, activation: str) -> jax.Array:
    act = parse_activation_fn(activation)
    if strategy == DownsamplingStrategy.CONV_MAX:
        x = nn.Conv(channels, kernel_size=(3, 3), strides=(1, 1))(x)
        return nn.max_pool(x, window_shape=(3, 3), strides=(2, 2), padding="SAME")
    if strategy == DownsamplingStrategy.LAYERNORM_RELU_CONV:
        x = nn.LayerNorm(use_scale=True)(x)
        x = act(x)
        return nn.Conv(channels, kernel_size=(3, 3), strides=(2, 2))(x)
    if strategy == DownsamplingStrategy.CONV:
        return nn.Conv(channels, kernel_size=(3, 3), strides=(2, 2))(x)
    raise ValueError(f"Unknown downsampling strategy '{strategy}'")


class VisualResNetTorso(nn.Module):
    """IMPALA-style conv ResNet over NHWC inputs with arbitrary leading dims."""

    channels_per_group: Sequence[int] = (16, 32, 32)
    blocks_per_group: Sequence[int] = (2, 2, 2)
    downsampling_strategy: str = DownsamplingStrategy.CONV_MAX
    activation: str = "relu"
    use_layer_norm: bool = False
    hidden_sizes: Sequence[int] = (256,)
    channel_first: bool = False

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = parse_activation_fn(self.activation)
        lead_shape = x.shape[:-3]
        x = x.reshape((-1,) + x.shape[-3:])
        if self.channel_first:
            x = jnp.transpose(x, (0, 2, 3, 1))
        for channels, blocks in zip(self.channels_per_group, self.blocks_per_group):
            x = _downsample(x, channels, self.downsampling_strategy, self.activation)
            for _ in range(blocks):
                x = ResidualBlock(channels, self.activation, self.use_layer_norm)(x)
        x = act(x)
        x = x.reshape(x.shape[0], -1)
        for size in self.hidden_sizes:
            x = nn.Dense(size, kernel_init=nn.initializers.orthogonal(jnp.sqrt(2.0)))(x)
            x = act(x)
        return x.reshape(lead_shape + x.shape[-1:])


class MLPResidualBlock(nn.Module):
    hidden_size: int
    activation: str = "relu"
    use_layer_norm: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = parse_activation_fn(self.activation)
        y = x
        for _ in range(2):
            if self.use_layer_norm:
                y = nn.LayerNorm(use_scale=True)(y)
            y = act(y)
            y = nn.Dense(self.hidden_size)(y)
        return x + y


class MLPResNetTorso(nn.Module):
    """Dense ResNet for vector observations."""

    num_blocks: int = 2
    hidden_size: int = 256
    activation: str = "relu"
    use_layer_norm: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = nn.Dense(self.hidden_size)(x)
        for _ in range(self.num_blocks):
            x = MLPResidualBlock(self.hidden_size, self.activation, self.use_layer_norm)(x)
        return x
