"""Distribution post-processors (reference stoix/networks/postprocessors.py:10-81):
wrap a distribution's sample/mode with a transform WITHOUT correcting log_prob —
explicitly not a bijector; used for simple action rescaling at act time."""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.ops import Distribution


class PostProcessedDistribution(Distribution):
    def __init__(self, distribution: Distribution, postprocessor: Callable[[jax.Array], jax.Array]):
        self.distribution = distribution
        self.postprocessor = postprocessor

    def sample(self, *, seed: jax.Array) -> jax.Array:
        return self.postprocessor(self.distribution.sample(seed=seed))

    def mode(self) -> jax.Array:
        return self.postprocessor(self.distribution.mode())

    def mean(self) -> jax.Array:
        return self.postprocessor(self.distribution.mean())

    def __getattr__(self, name: str) -> Any:
        # Guard private/self-referential names so object reconstruction can't
        # recurse before __dict__ exists (same fix as envs.core.Wrapper).
        if name.startswith("_") or name == "distribution":
            raise AttributeError(name)
        return getattr(self.distribution, name)


def rescale_to_spec(x: jax.Array, minimum: float, maximum: float) -> jax.Array:
    """Affine map from [-1, 1] to [minimum, maximum]."""
    scale = (maximum - minimum) / 2.0
    offset = (maximum + minimum) / 2.0
    return x * scale + offset


def clip_to_spec(x: jax.Array, minimum: float, maximum: float) -> jax.Array:
    return jnp.clip(x, minimum, maximum)


def tanh_to_spec(x: jax.Array, minimum: float, maximum: float) -> jax.Array:
    return rescale_to_spec(jnp.tanh(x), minimum, maximum)


def min_max_normalize(x: jax.Array, epsilon: float = 1e-5) -> jax.Array:
    x_min = jnp.min(x, axis=-1, keepdims=True)
    x_max = jnp.max(x, axis=-1, keepdims=True)
    return (x - x_min) / jnp.maximum(x_max - x_min, epsilon)


class ScalePostProcessor(nn.Module):
    minimum: float
    maximum: float
    scale_fn: Callable[[jax.Array, float, float], jax.Array] = tanh_to_spec

    @nn.compact
    def __call__(self, distribution: Distribution) -> PostProcessedDistribution:
        return PostProcessedDistribution(
            distribution, lambda x: self.scale_fn(x, self.minimum, self.maximum)
        )
