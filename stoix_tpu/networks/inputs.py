"""Input layers: adapt the Observation struct (or raw arrays) into the tensor a
torso consumes (reference stoix/networks/inputs.py:7-45)."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.envs.types import Observation


class ArrayInput(nn.Module):
    """Pass a raw array straight through."""

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        return x


class ObservationInput(nn.Module):
    """Select an attribute from the Observation struct (default: agent_view)."""

    feature: str = "agent_view"

    @nn.compact
    def __call__(self, observation: Observation) -> jax.Array:
        return getattr(observation, self.feature)


class EmbeddingActionInput(nn.Module):
    """Concatenate observation features with a continuous action — Q(s, a)
    critics for DDPG/TD3/SAC."""

    feature: str = "agent_view"

    @nn.compact
    def __call__(self, observation: Observation, action: jax.Array) -> jax.Array:
        return jnp.concatenate([getattr(observation, self.feature), action], axis=-1)


class EmbeddingActionOnehotInput(nn.Module):
    """Concatenate observation features with a one-hot discrete action."""

    num_actions: int
    feature: str = "agent_view"

    @nn.compact
    def __call__(self, observation: Observation, action: jax.Array) -> jax.Array:
        onehot = jax.nn.one_hot(action, self.num_actions)
        return jnp.concatenate([getattr(observation, self.feature), onehot], axis=-1)
