"""Disco agent network: shared torso + action-conditional LSTM transition +
five prediction heads.

Parity target: reference stoix/networks/specialised/disco103.py (the agent
model the DiscoRL meta-learned update rule drives — policy logits plus
categorical value/auxiliary predictions over per-action hidden states).

TPU-native notes: the action-conditional transition runs ONE LSTMCell apply
over a [batch * num_actions] folded axis (a single fused matmul batch on the
MXU) rather than looping actions; everything is static-shape.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class DiscoAgentOutput(NamedTuple):
    """The five prediction heads the disco update rule consumes
    (reference stoix/systems/disco_rl/disco_rl_types.py AgentOutput)."""

    logits: jax.Array  # [..., A]        policy
    q: jax.Array  # [..., A, B]          per-action categorical value
    y: jax.Array  # [..., B]             state categorical prediction
    z: jax.Array  # [..., A, B]          per-action auxiliary categorical
    aux_pi: jax.Array  # [..., A, A]     per-action auxiliary policy


class ActionConditionedLSTMTorso(nn.Module):
    """Root embedding -> one LSTM step per action, all actions in parallel
    (reference disco103.py LSTMActionConditionedTorso:13-110)."""

    num_actions: int
    lstm_size: int = 256
    root_mlp_sizes: Sequence[int] = ()
    activation: str = "relu"

    @nn.compact
    def __call__(self, embedding: jax.Array) -> jax.Array:
        from stoix_tpu.networks.utils import parse_activation_fn

        # Rank-agnostic: fold every leading dim (the evaluator applies the
        # network to single unbatched observations).
        lead = embedding.shape[:-1]
        x = embedding.reshape((-1, embedding.shape[-1]))
        batch = x.shape[0]

        act = parse_activation_fn(self.activation)
        for size in self.root_mlp_sizes:
            x = act(nn.Dense(size, kernel_init=nn.initializers.orthogonal(1.0))(x))
        cell = nn.Dense(
            self.lstm_size, kernel_init=nn.initializers.orthogonal(1.0), name="root_cell"
        )(x)
        carry = (jnp.tanh(cell), cell)

        # Fold actions into the batch: one LSTM apply for every (state, action).
        one_hot = jnp.eye(self.num_actions, dtype=cell.dtype)  # [A, A]
        actions = jnp.tile(one_hot, (batch, 1))  # [batch*A, A]
        carry = jax.tree.map(
            lambda c: jnp.repeat(c, repeats=self.num_actions, axis=0), carry
        )
        _, out = nn.LSTMCell(features=self.lstm_size, name="action_lstm")(
            carry, actions
        )
        return out.reshape(lead + (self.num_actions, self.lstm_size))


class DiscoAgentNetwork(nn.Module):
    """Shared torso + logits/y heads on the state embedding, q/z/aux_pi heads
    on the action-conditional embeddings (reference disco103.py:113-152)."""

    shared_torso: nn.Module
    action_conditional_torso: nn.Module
    logits_head: nn.Module
    q_head: nn.Module
    y_head: nn.Module
    z_head: nn.Module
    aux_pi_head: nn.Module

    def __call__(self, observation) -> DiscoAgentOutput:
        embedding = self.shared_torso(observation.agent_view)
        logits = self.logits_head(embedding)
        y = self.y_head(embedding)

        per_action = self.action_conditional_torso(embedding)  # [batch, A, H]
        q = self.q_head(per_action)
        z = self.z_head(per_action)
        aux_pi = self.aux_pi_head(per_action)
        return DiscoAgentOutput(logits=logits, q=q, y=y, z=z, aux_pi=aux_pi)
