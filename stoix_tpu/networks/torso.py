"""Torso networks (reference stoix/networks/torso.py:12-108).

TPU notes: MLP widths should be multiples of 128 where throughput matters (MXU
tiling); CNNTorso keeps NHWC layout (XLA's preferred conv layout on TPU) and
flattens leading batch dims automatically so the same module serves [B, ...]
and [T, B, ...] inputs.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks.layers import NoisyLinear
from stoix_tpu.networks.utils import parse_activation_fn


class MLPTorso(nn.Module):
    layer_sizes: Sequence[int] = (256, 256)
    activation: str = "silu"
    use_layer_norm: bool = False
    activate_final: bool = True
    kernel_init: str = "orthogonal"
    kernel_scale: float = 1.4142135  # sqrt(2)
    # "bfloat16" runs matmuls/activations in bf16 on the MXU while parameters
    # stay fp32 (flax Dense dtype semantics); outputs are cast back to fp32 so
    # downstream losses/collectives keep full precision.
    compute_dtype: str = "float32"

    def _kernel_init(self):
        if self.kernel_init == "orthogonal":
            return nn.initializers.orthogonal(self.kernel_scale)
        return nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = parse_activation_fn(self.activation)
        dtype = jnp.dtype(self.compute_dtype)
        for i, size in enumerate(self.layer_sizes):
            x = nn.Dense(size, kernel_init=self._kernel_init(), dtype=dtype)(x)
            if self.use_layer_norm:
                x = nn.LayerNorm(use_scale=True, dtype=dtype)(x)
            if i < len(self.layer_sizes) - 1 or self.activate_final:
                x = act(x)
        return x.astype(jnp.float32)


class NoisyMLPTorso(nn.Module):
    """MLP with factorized-Gaussian noisy linear layers (NoisyNets). Callers
    must supply an rng stream named "noise" unless sigma_zero == 0."""

    layer_sizes: Sequence[int] = (256, 256)
    activation: str = "relu"
    use_layer_norm: bool = False
    activate_final: bool = True
    sigma_zero: float = 0.5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = parse_activation_fn(self.activation)
        for i, size in enumerate(self.layer_sizes):
            x = NoisyLinear(size, sigma_zero=self.sigma_zero)(x)
            if self.use_layer_norm:
                x = nn.LayerNorm(use_scale=True)(x)
            if i < len(self.layer_sizes) - 1 or self.activate_final:
                x = act(x)
        return x


class CNNTorso(nn.Module):
    """NHWC conv stack followed by a flatten + MLP. Accepts inputs with any
    number of leading batch dims ([B, H, W, C], [T, B, H, W, C], ...)."""

    channel_sizes: Sequence[int] = (32, 64, 64)
    kernel_sizes: Sequence[int] = (8, 4, 3)
    strides: Sequence[int] = (4, 2, 1)
    activation: str = "relu"
    use_layer_norm: bool = False
    hidden_sizes: Sequence[int] = (256,)
    channel_first: bool = False
    compute_dtype: str = "float32"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        act = parse_activation_fn(self.activation)
        dtype = jnp.dtype(self.compute_dtype)
        lead_shape = x.shape[:-3]
        x = x.reshape((-1,) + x.shape[-3:])
        if self.channel_first:  # NCHW input -> NHWC for TPU-friendly convs
            x = jnp.transpose(x, (0, 2, 3, 1))
        for ch, k, s in zip(self.channel_sizes, self.kernel_sizes, self.strides):
            x = nn.Conv(ch, kernel_size=(k, k), strides=(s, s), dtype=dtype)(x)
            if self.use_layer_norm:
                x = nn.LayerNorm(use_scale=True, dtype=dtype)(x)
            x = act(x)
        x = x.reshape(x.shape[0], -1)
        for size in self.hidden_sizes:
            x = nn.Dense(size, kernel_init=nn.initializers.orthogonal(jnp.sqrt(2.0)), dtype=dtype)(x)
            x = act(x)
        return x.reshape(lead_shape + x.shape[-1:]).astype(jnp.float32)
