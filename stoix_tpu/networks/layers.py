"""Special layers: NoisyLinear (Rainbow) and StackedRNN
(reference stoix/networks/layers.py:16-169)."""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks.utils import parse_rnn_cell


class NoisyLinear(nn.Module):
    """Factorized Gaussian noisy linear layer (Fortunato et al. 2018).

    y = (μ_w + σ_w ⊙ (f(ε_in) f(ε_out)ᵀ)) x + μ_b + σ_b ⊙ f(ε_out),
    f(x) = sign(x) sqrt(|x|). Noise comes from the "noise" rng stream; when the
    stream is absent (evaluation), the layer runs deterministically with μ only.
    """

    features: int
    sigma_zero: float = 0.5

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        sigma_init = self.sigma_zero / jnp.sqrt(in_features)
        bound = 1.0 / jnp.sqrt(in_features)

        mu_w = self.param(
            "mu_w", nn.initializers.uniform(scale=2 * bound), (in_features, self.features)
        )
        mu_b = self.param("mu_b", nn.initializers.uniform(scale=2 * bound), (self.features,))
        sigma_w = self.param(
            "sigma_w", nn.initializers.constant(sigma_init), (in_features, self.features)
        )
        sigma_b = self.param("sigma_b", nn.initializers.constant(sigma_init), (self.features,))
        # uniform(scale) yields [0, scale); recenter to [-bound, bound).
        mu_w = mu_w - bound
        mu_b = mu_b - bound

        if self.has_rng("noise"):
            key = self.make_rng("noise")
            k_in, k_out = jax.random.split(key)
            f = lambda e: jnp.sign(e) * jnp.sqrt(jnp.abs(e))
            eps_in = f(jax.random.normal(k_in, (in_features,)))
            eps_out = f(jax.random.normal(k_out, (self.features,)))
            w = mu_w + sigma_w * jnp.outer(eps_in, eps_out)
            b = mu_b + sigma_b * eps_out
        else:
            w, b = mu_w, mu_b
        return x @ w + b


class StackedRNN(nn.Module):
    """A stack of RNN cells applied per step, carrying a tuple of hidden states
    (used by the MuZero world-model dynamics)."""

    hidden_size: int
    num_layers: int = 2
    cell_type: str = "lstm"

    def setup(self) -> None:
        cell_cls = parse_rnn_cell(self.cell_type)
        self.cells = [cell_cls(features=self.hidden_size) for _ in range(self.num_layers)]

    def __call__(self, states: Sequence[Any], x: jax.Array) -> Tuple[Tuple[Any, ...], jax.Array]:
        new_states = []
        for cell, state in zip(self.cells, states):
            state, x = cell(state, x)
            new_states.append(state)
        return tuple(new_states), x

    def initialize_carry(self, key: jax.Array, input_shape: Tuple[int, ...]) -> Tuple[Any, ...]:
        # Zero carries built directly (instantiating cells here would register
        # submodules when called from a bound parent module).
        del key
        shape = input_shape[:-1] + (self.hidden_size,)
        if self.cell_type in ("lstm", "optimised_lstm"):
            return tuple((jnp.zeros(shape), jnp.zeros(shape)) for _ in range(self.num_layers))
        return tuple(jnp.zeros(shape) for _ in range(self.num_layers))
