"""Dueling Q-network heads (reference stoix/networks/dueling.py:15-124)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks.torso import MLPTorso, NoisyMLPTorso
from stoix_tpu.ops import distributions as dists


class DuelingQNetwork(nn.Module):
    """Q(s,a) = V(s) + A(s,a) - mean_a A(s,a)."""

    action_dim: int
    epsilon: float = 0.1
    layer_sizes: Sequence[int] = (128,)
    activation: str = "relu"

    @nn.compact
    def __call__(
        self,
        embedding: jax.Array,
        epsilon: Optional[jax.Array] = None,
        action_mask: Optional[jax.Array] = None,
    ) -> dists.EpsilonGreedy:
        value = MLPTorso((*self.layer_sizes, 1), self.activation, activate_final=False)(embedding)
        adv = MLPTorso((*self.layer_sizes, self.action_dim), self.activation, activate_final=False)(
            embedding
        )
        q_values = value + adv - jnp.mean(adv, axis=-1, keepdims=True)
        eps = self.epsilon if epsilon is None else epsilon
        return dists.EpsilonGreedy(q_values, eps, mask=action_mask)


class DistributionalDuelingQNetwork(nn.Module):
    """Dueling C51: atoms for value and advantage combined then softmaxed."""

    action_dim: int
    num_atoms: int = 51
    vmin: float = -10.0
    vmax: float = 10.0
    epsilon: float = 0.1
    layer_sizes: Sequence[int] = (128,)
    activation: str = "relu"

    @nn.compact
    def __call__(
        self,
        embedding: jax.Array,
        epsilon: Optional[jax.Array] = None,
        action_mask: Optional[jax.Array] = None,
    ) -> Tuple[dists.EpsilonGreedy, jax.Array, jax.Array]:
        atoms = jnp.linspace(self.vmin, self.vmax, self.num_atoms)
        value = MLPTorso((*self.layer_sizes, self.num_atoms), self.activation, activate_final=False)(
            embedding
        )
        adv = MLPTorso(
            (*self.layer_sizes, self.action_dim * self.num_atoms), self.activation, activate_final=False
        )(embedding)
        adv = adv.reshape(embedding.shape[:-1] + (self.action_dim, self.num_atoms))
        logits = value[..., None, :] + adv - jnp.mean(adv, axis=-2, keepdims=True)
        q_values = jnp.sum(jax.nn.softmax(logits, axis=-1) * atoms, axis=-1)
        eps = self.epsilon if epsilon is None else epsilon
        return dists.EpsilonGreedy(q_values, eps, mask=action_mask), logits, atoms


class NoisyDistributionalDuelingQNetwork(nn.Module):
    """Rainbow head: noisy layers + dueling + C51 (reference dueling.py:90-124).
    Requires the "noise" rng stream during training."""

    action_dim: int
    num_atoms: int = 51
    vmin: float = -10.0
    vmax: float = 10.0
    epsilon: float = 0.0
    layer_sizes: Sequence[int] = (128,)
    activation: str = "relu"
    sigma_zero: float = 0.5

    @nn.compact
    def __call__(
        self,
        embedding: jax.Array,
        epsilon: Optional[jax.Array] = None,
        action_mask: Optional[jax.Array] = None,
    ) -> Tuple[dists.EpsilonGreedy, jax.Array, jax.Array]:
        atoms = jnp.linspace(self.vmin, self.vmax, self.num_atoms)
        value = NoisyMLPTorso(
            (*self.layer_sizes, self.num_atoms), self.activation, activate_final=False,
            sigma_zero=self.sigma_zero,
        )(embedding)
        adv = NoisyMLPTorso(
            (*self.layer_sizes, self.action_dim * self.num_atoms), self.activation,
            activate_final=False, sigma_zero=self.sigma_zero,
        )(embedding)
        adv = adv.reshape(embedding.shape[:-1] + (self.action_dim, self.num_atoms))
        logits = value[..., None, :] + adv - jnp.mean(adv, axis=-2, keepdims=True)
        q_values = jnp.sum(jax.nn.softmax(logits, axis=-1) * atoms, axis=-1)
        eps = self.epsilon if epsilon is None else epsilon
        return dists.EpsilonGreedy(q_values, eps, mask=action_mask), logits, atoms
