"""Specialised networks: permutation-invariant entity encoding.

The reference ships a kinetix-specific entity encoder
(reference stoix/networks/specialised/kinetix.py:13 — per-entity-type Dense
embeddings with a type one-hot, mask-zeroed entities, multi-head pooling).
This module provides the TPU-first equivalent as a *generic* set encoder: any
observation made of typed entity sets with validity masks works, not just
kinetix's four fixed types.

Design (all MXU-friendly batched matmuls, no per-entity Python):
  1. each entity type t with features [..., N_t, F_t] is embedded by its own
     Dense to a shared width, and a learned type embedding is added (replacing
     the reference's one-hot-appended-to-features trick);
  2. types concatenate along the entity axis -> [..., E, D] with mask [..., E];
  3. pooling is multi-head attention with learned head queries (PMA-style):
     masked softmax over entities per head, weighted sum, heads concatenated
     and projected to hidden_dim. Invalid entities get -inf scores, so the
     output is exactly invariant to both entity order and padding content.

Used as a `pre_torso` via config `_target_`, same as any torso module.
"""

from __future__ import annotations

from typing import Dict

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.networks.utils import parse_activation_fn


class EntityEncoder(nn.Module):
    """Permutation-invariant encoder over typed entity sets.

    Input: a dict mapping entity-type name -> [..., N_t, F_t] feature arrays.
    For each type, an optional "<name>_mask" key of shape [..., N_t] marks
    valid entities (missing mask = all valid). Leading batch dims are free.

    Output: [..., hidden_dim].
    """

    hidden_dim: int = 256
    num_heads: int = 4
    entity_embed_dim: int = 64
    activation: str = "tanh"

    @nn.compact
    def __call__(self, entities: Dict[str, jax.Array]) -> jax.Array:
        act = parse_activation_fn(self.activation)
        init = nn.initializers.orthogonal(jnp.sqrt(2.0))

        type_names = sorted(k for k in entities if not k.endswith("_mask"))
        if not type_names:
            raise ValueError("EntityEncoder needs at least one entity-type array")

        embeds = []
        masks = []
        for i, name in enumerate(type_names):
            feats = entities[name]
            emb = act(
                nn.Dense(self.entity_embed_dim, kernel_init=init, name=f"embed_{name}")(feats)
            )
            type_emb = self.param(
                f"type_{name}", nn.initializers.normal(0.02), (self.entity_embed_dim,)
            )
            embeds.append(emb + type_emb)
            mask = entities.get(f"{name}_mask")
            if mask is None:
                mask = jnp.ones(feats.shape[:-1], feats.dtype)
            masks.append(mask)

        x = jnp.concatenate(embeds, axis=-2)  # [..., E, D]
        mask = jnp.concatenate(masks, axis=-1)  # [..., E]

        # Multi-head attention pooling with learned per-head queries.
        scores = nn.Dense(self.num_heads, kernel_init=init, name="pool_scores")(x)  # [..., E, H]
        neg_inf = jnp.finfo(scores.dtype).min
        scores = jnp.where(mask[..., None] > 0, scores, neg_inf)
        weights = jax.nn.softmax(scores, axis=-2)  # softmax over entities
        # Guard the all-masked case (softmax of all -inf): zero the weights.
        weights = jnp.where(mask[..., None] > 0, weights, 0.0)
        pooled = jnp.einsum("...eh,...ed->...hd", weights, x)  # [..., H, D]
        flat = pooled.reshape(*pooled.shape[:-2], self.num_heads * self.entity_embed_dim)
        return act(nn.Dense(self.hidden_dim, kernel_init=init, name="out")(flat))
