from stoix_tpu.networks import (
    base,
    dueling,
    heads,
    inputs,
    layers,
    model_based,
    postprocessors,
    resnet,
    torso,
    utils,
)

__all__ = [
    "base",
    "dueling",
    "heads",
    "inputs",
    "layers",
    "model_based",
    "postprocessors",
    "resnet",
    "torso",
    "utils",
]
