"""Actor/critic shells and recurrent network plumbing
(reference stoix/networks/base.py:18-252).

A network = input_layer -> torso -> head. Systems instantiate these from config
(see stoix_tpu.utils.config.instantiate) and use `.init` / `.apply` as usual.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from stoix_tpu.envs.types import Observation
from stoix_tpu.networks.utils import parse_rnn_cell


class FeedForwardActor(nn.Module):
    """input -> torso -> action head, returning a distribution."""

    action_head: nn.Module
    torso: nn.Module
    input_layer: nn.Module

    @nn.compact
    def __call__(self, observation: Any, *head_args: Any, **head_kwargs: Any):
        embedding = self.torso(self.input_layer(observation))
        if isinstance(observation, Observation) and _head_takes_mask(self.action_head):
            head_kwargs.setdefault("action_mask", observation.action_mask)
        return self.action_head(embedding, *head_args, **head_kwargs)


def _head_takes_mask(head: nn.Module) -> bool:
    import inspect

    try:
        return "action_mask" in inspect.signature(type(head).__call__).parameters
    except (ValueError, TypeError):
        return False


class FeedForwardCritic(nn.Module):
    """input -> torso -> critic head, returning values (or value dists)."""

    critic_head: nn.Module
    torso: nn.Module
    input_layer: nn.Module

    @nn.compact
    def __call__(self, observation: Any, *inputs: Any):
        embedding = self.torso(self.input_layer(observation, *inputs))
        return self.critic_head(embedding)


class FeedForwardActorCritic(nn.Module):
    """Shared torso producing (policy distribution, value)."""

    shared_head: nn.Module  # a PolicyValueHead
    torso: nn.Module
    input_layer: nn.Module

    @nn.compact
    def __call__(self, observation: Any):
        embedding = self.torso(self.input_layer(observation))
        return self.shared_head(embedding)


class CompositeNetwork(nn.Module):
    """Sequential composition of arbitrary modules (reference base.py:62-84)."""

    layers: Sequence[nn.Module]

    @nn.compact
    def __call__(self, *args: Any):
        out = self.layers[0](*args)
        for layer in self.layers[1:]:
            out = layer(out)
        return out


class MultiNetwork(nn.Module):
    """Parallel heads over the same inputs, stacked on a new leading output axis
    — used for twin-Q critics (reference base.py:87-121)."""

    networks: Sequence[nn.Module]

    @nn.compact
    def __call__(self, *args: Any) -> jax.Array:
        outs = [jnp.expand_dims(net(*args), axis=-1) for net in self.networks]
        return jnp.concatenate(outs, axis=-1)


class ScannedRNN(nn.Module):
    """Time-major RNN unroll via nn.scan with per-step hidden-state reset where
    `done` is set (reference base.py:124-159). Input: (hstate, (xs, dones))
    with xs [T, B, F], dones [T, B]. Returns (final_hstate, outputs [T, B, H]).
    """

    hidden_size: int
    cell_type: str = "gru"

    @nn.compact
    def __call__(self, hstate: Any, inputs: Tuple[jax.Array, jax.Array]):
        cell_cls = parse_rnn_cell(self.cell_type)

        def step(cell: nn.Module, carry: Any, inp: Tuple[jax.Array, jax.Array]):
            x, done = inp
            fresh = cell.initialize_carry(jax.random.PRNGKey(0), x.shape)
            carry = jax.tree.map(
                lambda f, c: jnp.where(done[..., None], f, c), fresh, carry
            )
            carry, out = cell(carry, x)
            return carry, out

        scan = nn.scan(
            step,
            variable_broadcast="params",
            in_axes=0,
            out_axes=0,
            split_rngs={"params": False},
        )
        return scan(cell_cls(features=self.hidden_size), hstate, inputs)

    @staticmethod
    def initialize_carry(cell_type: str, hidden_size: int, batch_shape: Tuple[int, ...]) -> Any:
        cell = parse_rnn_cell(cell_type)(features=hidden_size)
        return cell.initialize_carry(jax.random.PRNGKey(0), batch_shape + (hidden_size,))


class RecurrentActor(nn.Module):
    """pre_torso -> RNN -> post_torso -> action head over a time-major sequence
    (reference base.py:162-192)."""

    action_head: nn.Module
    rnn: ScannedRNN
    pre_torso: nn.Module
    post_torso: nn.Module
    input_layer: nn.Module

    @nn.compact
    def __call__(self, hstate: Any, observation_done: Tuple[Any, jax.Array]):
        observation, done = observation_done
        x = self.pre_torso(self.input_layer(observation))
        hstate, x = self.rnn(hstate, (x, done))
        x = self.post_torso(x)
        kwargs = {}
        if isinstance(observation, Observation) and _head_takes_mask(self.action_head):
            kwargs["action_mask"] = observation.action_mask
        return hstate, self.action_head(x, **kwargs)


class RecurrentCritic(nn.Module):
    critic_head: nn.Module
    rnn: ScannedRNN
    pre_torso: nn.Module
    post_torso: nn.Module
    input_layer: nn.Module

    @nn.compact
    def __call__(self, hstate: Any, observation_done: Tuple[Any, jax.Array]):
        observation, done = observation_done
        x = self.pre_torso(self.input_layer(observation))
        hstate, x = self.rnn(hstate, (x, done))
        x = self.post_torso(x)
        return hstate, self.critic_head(x)


def chained_torsos(torsos: Sequence[nn.Module]) -> CompositeNetwork:
    """Compose torso modules sequentially (reference base.py:225-252)."""
    return CompositeNetwork(layers=list(torsos))
