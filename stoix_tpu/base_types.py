"""Shared type vocabulary (reference stoix/base_types.py:32-220).

NamedTuple state/transition structs used across systems. All states hold GLOBAL
(mesh-sharded) arrays; there is no leading [device, update_batch] axis pair as
in the reference — sharding is carried by the arrays' NamedShardings instead.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple

import jax
from stoix_tpu.envs.types import Observation, TimeStep  # noqa: F401  (re-export)

Parameters = Any
OptStates = Any
HiddenState = Any
Metrics = Dict[str, jax.Array]


class OnlineAndTarget(NamedTuple):
    online: Parameters
    target: Parameters


class ActorCriticParams(NamedTuple):
    actor_params: Parameters
    critic_params: Parameters


class ActorCriticOptStates(NamedTuple):
    actor_opt_state: OptStates
    critic_opt_state: OptStates


class OnPolicyLearnerState(NamedTuple):
    params: Any
    opt_states: Any
    key: jax.Array
    env_state: Any
    timestep: TimeStep


class OffPolicyLearnerState(NamedTuple):
    params: Any
    opt_states: Any
    buffer_state: Any
    key: jax.Array
    env_state: Any
    timestep: TimeStep


class RNNLearnerState(NamedTuple):
    params: Any
    opt_states: Any
    key: jax.Array
    env_state: Any
    timestep: TimeStep
    done: jax.Array
    truncated: jax.Array
    hstates: Any
    obs_stats: Any = None  # observation running statistics (rec_ppo)


class RNNOffPolicyLearnerState(NamedTuple):
    params: Any
    opt_states: Any
    buffer_state: Any
    key: jax.Array
    env_state: Any
    timestep: TimeStep
    done: jax.Array
    truncated: jax.Array
    hstates: Any


class PPOTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    value: jax.Array
    reward: jax.Array
    log_prob: jax.Array
    obs: Any
    next_obs: Any
    info: Dict[str, Any]


class Transition(NamedTuple):
    """Generic off-policy transition (DQN family)."""

    obs: Any
    action: jax.Array
    reward: jax.Array
    done: jax.Array
    next_obs: Any
    info: Dict[str, Any]


class ExperimentOutput(NamedTuple):
    learner_state: Any
    episode_metrics: Metrics
    train_metrics: Metrics


ActorApply = Callable[..., Any]
CriticApply = Callable[..., jax.Array]
LearnerFn = Callable[[Any], ExperimentOutput]
EvalFn = Callable[..., Metrics]
