"""Evaluator (reference stoix/evaluator.py:87-416).

Runs `num_eval_episodes` episodes to completion (lax.while_loop keyed on
timestep.last(), reference evaluator.py:152) with episodes vmapped within each
shard and sharded over the mesh's data axis via shard_map — the TPU-native
replacement for the reference's pmapped evaluator. The absolute-metric
evaluator is the same function with eval_multiplier=10.

Caveat preserved from the reference (README.md:197): non-terminating envs make
the while_loop spin forever — give eval envs a step limit.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu.envs.core import Environment
from stoix_tpu.parallel.mesh import shard_map

# act_fn(params, observation, key) -> action  (single unbatched observation)
ActFn = Callable[[Any, Any, jax.Array], jax.Array]


class _EvalCarry(NamedTuple):
    env_state: Any
    timestep: Any
    key: jax.Array


def get_distribution_act_fn(
    config: Any,
    actor_apply: Callable[..., Any],
    rngs: Optional[Dict[str, jax.Array]] = None,
) -> ActFn:
    """Greedy (mode) or sampled acting from a distribution-returning network
    (reference evaluator.py:48-67)."""

    greedy = bool(config.arch.get("evaluation_greedy", False))

    def act(params: Any, observation: Any, key: jax.Array) -> jax.Array:
        if rngs is None:
            dist = actor_apply(params, observation)
        else:
            dist = actor_apply(params, observation, rngs=rngs)
        return dist.mode() if greedy else dist.sample(seed=key)

    return act


def _make_eval_reset_fn(eval_env: Environment, config: Any):
    """Episode-reset function for evaluation: (key, episode_index) -> (state, ts).

    By default the env's own reset. An env-specific override (e.g. fixed
    evaluation levels, the reference's kinetix hook at evaluator.py:365-372)
    is instantiated from config.env.eval_reset_fn as either
      callable(env, key) -> (state, timestep), or
      callable(env, key, episode_index) -> (state, timestep)
    — the 3-arg form receives the global episode index so hooks can tile a
    fixed level list deterministically across episodes (see
    make_tiled_eval_reset_fn; reference wrappers/kinetix.py:15-51)."""
    hook_cfg = config.env.get("eval_reset_fn")
    if not hook_cfg:
        return lambda key, idx: eval_env.reset(key)
    import inspect

    from stoix_tpu.utils.config import instantiate

    hook = instantiate(hook_cfg)
    try:
        n_params = len(inspect.signature(hook).parameters)
    except (TypeError, ValueError):
        n_params = 2
    if n_params >= 3:
        return lambda key, idx: hook(eval_env, key, idx)
    return lambda key, idx: hook(eval_env, key)


def make_tiled_eval_reset_fn(levels: Any):
    """Eval-reset hook that cycles a fixed list of levels across episodes
    (the reference's kinetix list-mode eval reset, wrappers/kinetix.py:15-51,
    generalized to any env exposing reset_to_level(level, key)).

    `levels` is a sequence of per-level values — scalars, arrays, or pytrees
    (kinetix-style level states) — or an already-stacked pytree whose leaves
    have a leading level axis. Episode i resets to level i % n_levels, so with
    num_eval_episodes a multiple of n_levels every level is evaluated equally
    often.
    """
    import numpy as np

    if isinstance(levels, (list, tuple)):
        stacked = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *levels)
        n_levels = len(levels)
    else:
        stacked = levels
        n_levels = int(np.asarray(jax.tree.leaves(levels)[0]).shape[0])

    def hook(env: Environment, key: jax.Array, episode_index: jax.Array):
        level = jax.tree.map(lambda x: x[episode_index % n_levels], stacked)
        return env.reset_to_level(level, key)

    return hook


def get_ff_evaluator_fn(
    eval_env: Environment,
    act_fn: ActFn,
    config: Any,
    mesh: Mesh,
    eval_multiplier: int = 1,
):
    """Build the sharded evaluator: (params, key) -> episode metrics dict with
    leaves shaped [global_eval_episodes]."""

    n_shards = int(mesh.shape["data"])
    episodes_global = int(config.arch.num_eval_episodes) * eval_multiplier
    if episodes_global % n_shards != 0:
        episodes_global = ((episodes_global // n_shards) + 1) * n_shards
    per_shard = episodes_global // n_shards
    reset_fn = _make_eval_reset_fn(eval_env, config)
    # Fixed-trip-count episode loop (SURVEY §7.3.6): under vmap, a while_loop
    # runs every episode until the LONGEST one ends (divergence cost); with a
    # known step limit a lax.scan with result masking is fully static and
    # TPU-friendly. Enabled via arch.eval_max_steps.
    eval_max_steps = config.arch.get("eval_max_steps")

    def eval_one_episode(params: Any, key: jax.Array, idx: jax.Array) -> Dict[str, jax.Array]:
        reset_key, act_key = jax.random.split(key)
        env_state, timestep = reset_fn(reset_key, idx)

        def body(carry: _EvalCarry) -> _EvalCarry:
            key, act_key = jax.random.split(carry.key)
            action = act_fn(params, carry.timestep.observation, act_key)
            env_state, timestep = eval_env.step(carry.env_state, action)
            return _EvalCarry(env_state, timestep, key)

        if eval_max_steps:

            def scan_body(carry: _EvalCarry, _):
                stepped = body(carry)
                # Freeze the carry once the episode has ended; the env is
                # still stepped but its results are discarded, keeping the
                # trip count static for XLA.
                done = carry.timestep.last()  # scalar — broadcasts over leaves
                frozen = jax.tree.map(lambda a, b: jnp.where(done, a, b), carry, stepped)
                return frozen, None

            final, _ = jax.lax.scan(
                scan_body, _EvalCarry(env_state, timestep, act_key), None,
                int(eval_max_steps),
            )
            # Episodes still running at the step cap are truncated AT the cap:
            # their running return/length are reported as-is, and the
            # episode_finished metric surfaces how many were cut short (a
            # mean < 1.0 in the logs means eval_max_steps is too small for
            # this env — not a silent condition).
            finished = final.timestep.last()
        else:

            def cond(carry: _EvalCarry) -> jax.Array:
                return ~carry.timestep.last()

            final = jax.lax.while_loop(cond, body, _EvalCarry(env_state, timestep, act_key))
            finished = jnp.ones((), bool)
        metrics = final.timestep.extras["episode_metrics"]
        return {
            "episode_return": metrics["episode_return"],
            "episode_length": metrics["episode_length"],
            "episode_finished": finished.astype(jnp.float32),
        }

    def _shard_eval(params: Any, keys: jax.Array, idxs: jax.Array) -> Dict[str, jax.Array]:
        return jax.vmap(eval_one_episode, in_axes=(None, 0, 0))(params, keys, idxs)

    sharded = jax.jit(
        shard_map(
            _shard_eval,
            mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=P("data"),
            check_vma=False,  # while_loop carries mix replicated and varying leaves
        )
    )

    def evaluator(params: Any, key: jax.Array) -> Dict[str, jax.Array]:
        keys = jax.random.split(key, episodes_global)
        return sharded(params, keys, jnp.arange(episodes_global))

    # Pure-JAX and stateless: the runner may inline this into the jitted learn
    # program under arch.fused_eval (RNN/stateful evaluators never set this —
    # they fall back to the snapshot-overlap path, systems/runner.py).
    evaluator.supports_fusion = True
    return evaluator


def get_stateful_evaluator_fn(env_factory: Any, act_fn: ActFn, config: Any):
    """Evaluator for stateful env backends with no JAX twin (EnvPool /
    Gymnasium pools): drives one vectorized pool host-side until
    `arch.num_eval_episodes` episodes conclude, acting through the same
    act_fn as the sharded evaluator. Returns the same metrics contract
    ({"episode_return": [episodes]}), so AsyncEvaluator and the run loop are
    agnostic to which evaluator backs them (the reference's Sebulba evaluates
    EnvPool Atari on factory envs the same way, stoix/evaluator.py)."""
    import numpy as np

    episodes_needed = int(config.arch.num_eval_episodes)
    envs = env_factory(episodes_needed)
    jit_act = jax.jit(act_fn)
    # Host-loop safety cap: generous multiple of any sane episode length so a
    # never-terminating pool cannot hang the evaluator thread.
    max_host_steps = int(config.arch.get("eval_max_steps") or 0) or 100_000

    def evaluator(params: Any, key: jax.Array) -> Dict[str, jax.Array]:
        ts = envs.reset()
        returns: list = []
        for _ in range(max_host_steps):
            if len(returns) >= episodes_needed:
                break
            key, act_key = jax.random.split(key)
            action = jit_act(params, ts.observation, act_key)
            ts = envs.step(np.asarray(action))
            em = ts.extras["episode_metrics"]
            concluded = np.asarray(em["is_terminal_step"]).astype(bool)
            returns.extend(np.asarray(em["episode_return"])[concluded].tolist())
        if not returns:
            returns = [float("nan")]  # visible in logs, never silently zero
        return {"episode_return": jnp.asarray(returns[:episodes_needed])}

    return evaluator


def get_rnn_evaluator_fn(
    eval_env: Environment,
    rnn_act_fn: Callable[..., Tuple[Any, jax.Array]],
    config: Any,
    mesh: Mesh,
    init_hstate_fn: Callable[[], Any],
    eval_multiplier: int = 1,
):
    """Recurrent evaluator: carries the hidden state through the episode
    (reference evaluator.py:209-344). rnn_act_fn(params, hstate, obs, done, key)
    -> (hstate, action)."""

    n_shards = int(mesh.shape["data"])
    episodes_global = int(config.arch.num_eval_episodes) * eval_multiplier
    if episodes_global % n_shards != 0:
        episodes_global = ((episodes_global // n_shards) + 1) * n_shards

    reset_fn = _make_eval_reset_fn(eval_env, config)

    def eval_one_episode(params: Any, key: jax.Array, idx: jax.Array) -> Dict[str, jax.Array]:
        reset_key, act_key = jax.random.split(key)
        env_state, timestep = reset_fn(reset_key, idx)
        hstate = init_hstate_fn()

        def cond(carry) -> jax.Array:
            return ~carry[1].last()

        def body(carry):
            env_state, timestep, hstate, key = carry
            key, act_key = jax.random.split(key)
            hstate, action = rnn_act_fn(
                params, hstate, timestep.observation, timestep.last(), act_key
            )
            env_state, timestep = eval_env.step(env_state, action)
            return (env_state, timestep, hstate, key)

        final = jax.lax.while_loop(cond, body, (env_state, timestep, hstate, act_key))
        metrics = final[1].extras["episode_metrics"]
        return {
            "episode_return": metrics["episode_return"],
            "episode_length": metrics["episode_length"],
        }

    def _shard_eval(params: Any, keys: jax.Array, idxs: jax.Array) -> Dict[str, jax.Array]:
        return jax.vmap(eval_one_episode, in_axes=(None, 0, 0))(params, keys, idxs)

    sharded = jax.jit(
        shard_map(
            _shard_eval, mesh=mesh, in_specs=(P(), P("data"), P("data")), out_specs=P("data"),
            check_vma=False,
        )
    )

    def evaluator(params: Any, key: jax.Array) -> Dict[str, jax.Array]:
        keys = jax.random.split(key, episodes_global)
        return sharded(params, keys, jnp.arange(episodes_global))

    return evaluator


def evaluator_setup(
    eval_env: Environment,
    act_fn: ActFn,
    config: Any,
    mesh: Mesh,
) -> Tuple[Any, Any]:
    """Returns (evaluator, absolute_metric_evaluator) — the latter runs
    eval_multiplier x episodes (reference evaluator.py:347-416)."""
    evaluator = get_ff_evaluator_fn(eval_env, act_fn, config, mesh)
    absolute_evaluator = get_ff_evaluator_fn(
        eval_env,
        act_fn,
        config,
        mesh,
        eval_multiplier=int(config.arch.get("absolute_metric_multiplier", 10)),
    )
    return evaluator, absolute_evaluator
