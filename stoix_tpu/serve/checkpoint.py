"""Checkpoint -> servable policy (docs/DESIGN.md §2.8).

A trained policy's life after training starts here: given EITHER an orbax
store (what `logger.checkpointing.save_model` writes) or a fleet local-shard
emergency store (resilience/fleet.py), rebuild the actor network from the
TRAINING config and restore just the actor-params subtree through the
topology-elastic machinery (utils/checkpointing.read_host_leaves +
place_host_leaves): leaves materialize to host, match by normalized
tree-path, and re-place onto whatever devices the SERVER runs — any
checkpoint serves on any mesh, params bit-identical (PR 4's guarantee,
pinned for the serving path in tests/test_serve.py).

Where the training config comes from, in priority order:
  1. `arch.serve.checkpoint.train_config` (+ train_overrides) — an explicit
     root yaml, required for emergency stores (they carry no metadata);
  2. the orbax store's own root metadata — the Checkpointer saves the FULL
     composed training config there, so a plain `serve` launch needs nothing
     but the store path.

The restored subtree keeps the training-side [update_batch] leading axis
while matching (the store's shapes are authoritative); replica 0 is served —
gradient pmean over the ("batch", "data") axes keeps all replicas
bit-identical during training, so replica choice cannot matter.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import numpy as np

from stoix_tpu.observability import get_logger
from stoix_tpu.resilience import fleet
from stoix_tpu.resilience.errors import CheckpointIntegrityError
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.checkpointing import place_host_leaves, read_host_leaves

DEFAULT_PARAMS_PATH = "params/actor_params"
OBS_STATS_PATH = "obs_stats"


def build_actor(config: Any, env: Any):
    """Instantiate the actor network exactly as learner_setup does (the
    PPO-family template, systems/ppo/anakin/ff_ppo.py): config.network's
    actor_network block with env-inferred head kwargs."""
    from stoix_tpu.networks.base import FeedForwardActor
    from stoix_tpu.systems import anakin

    net_cfg = config.network
    return FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )


def store_metadata(path: str) -> Dict[str, Any]:
    """The custom metadata dict an orbax store root carries ({} when absent
    or unreadable). The training Checkpointer writes the full composed config
    there, which is what makes `serve` self-describing."""
    import orbax.checkpoint as ocp

    try:
        manager = ocp.CheckpointManager(os.path.abspath(path))
    except Exception as exc:  # noqa: BLE001 — any unreadable store => no metadata
        get_logger("stoix_tpu.serve").warning(
            "[serve] could not open store metadata at %s (%s: %s)",
            path, type(exc).__name__, exc,
        )
        return {}
    try:
        meta = manager.metadata()
        custom = getattr(meta, "custom_metadata", meta)
        return dict(custom or {})
    finally:
        manager.close()


class PolicySource:
    """Where serving params come from — an orbax store directory (the
    model dir holding numeric step subdirectories) or a fleet emergency
    store. Re-loadable: the hot-swap watcher polls latest_step() and calls
    load() again when the store advances."""

    def __init__(
        self,
        path: str,
        templates: Dict[Tuple[str, ...], Any],
        bundle: Callable[[Dict[Tuple[str, ...], Any]], Any],
    ):
        self.path = str(path)
        self._templates = templates
        self._bundle = bundle
        self.is_emergency = fleet.is_emergency_store(self.path)

    def latest_step(self) -> Optional[int]:
        """Newest step available in the store (None when empty/missing)."""
        if self.is_emergency:
            return fleet.emergency_step(self.path)
        try:
            steps = [
                int(entry)
                for entry in os.listdir(self.path)
                if entry.isdigit() and os.path.isdir(os.path.join(self.path, entry))
            ]
        except OSError:
            return None
        return max(steps) if steps else None

    def _raw_leaves(self, step: Optional[int]) -> Tuple[Dict[Tuple[str, ...], Any], int]:
        if self.is_emergency:
            raw, casts, found = fleet.read_emergency_raw(self.path)
            if step is not None and found != int(step):
                # An emergency store holds exactly ONE step; an explicit
                # timestep it cannot honor must refuse, not silently serve a
                # different policy than the operator pinned.
                raise FileNotFoundError(
                    f"emergency store {self.path} holds step {found}, not "
                    f"the requested timestep {step}"
                )
            template_dtypes = {
                key: getattr(leaf, "dtype", np.asarray(leaf).dtype)
                for prefix, template in self._templates.items()
                for key, leaf in _flatten_with_prefix(template, prefix).items()
            }
            for key in casts:
                joined = tuple(key.split("/"))
                if key in raw and joined in template_dtypes:
                    raw[key] = raw[key].astype(template_dtypes[joined])
            return {tuple(k.split("/")): v for k, v in raw.items()}, found
        found = int(step) if step is not None else self.latest_step()
        if found is None:
            raise FileNotFoundError(f"no checkpoint steps under {self.path}")
        raw = read_host_leaves(self.path, found)
        # Digest verification when the store carries a manifest
        # (docs/DESIGN.md §2.9): a bit-rotted or half-synced checkpoint is
        # REJECTED here — the hot-swap watcher counts the error and keeps
        # serving the params it has — instead of being swapped into live
        # traffic. (Emergency stores verify inside fleet.read_emergency_raw.)
        from stoix_tpu.resilience import integrity
        from stoix_tpu.utils.checkpointing import saved_digest_record

        record = saved_digest_record(self.path).get(found) or {}
        if record:
            mismatched = integrity.verify_digests(
                {"/".join(key): arr for key, arr in raw.items()}, record
            )
            if mismatched:
                raise CheckpointIntegrityError(
                    found,
                    f"store {self.path} failed sha256 verification for "
                    f"{len(mismatched)} leaf(s): {', '.join(mismatched[:5])}"
                    f"{'...' if len(mismatched) > 5 else ''}",
                    kind="digest",
                )
        return raw, found

    def load(self, step: Optional[int] = None) -> Tuple[Any, int]:
        """Restore the configured subtrees at `step` (None = newest) and
        return (engine params, step). Every template leaf must match — a
        serving params subtree with reinitialized leaves would silently serve
        garbage, so partial matches raise CheckpointIntegrityError."""
        raw_by_path, found = self._raw_leaves(step)
        loaded: Dict[Tuple[str, ...], Any] = {}
        for prefix, template in self._templates.items():
            sub = {
                key[len(prefix):]: value
                for key, value in raw_by_path.items()
                if key[: len(prefix)] == prefix
            }
            placed, _matched, reinitialized, _reinit_keys = place_host_leaves(
                sub, template, found
            )
            if reinitialized:
                raise CheckpointIntegrityError(
                    found,
                    f"serving subtree {'/'.join(prefix)} has "
                    f"{len(reinitialized)} unmatched leaf(s) — refusing to "
                    f"serve a partially restored policy: "
                    f"{'; '.join(reinitialized)}",
                )
            # Serve replica 0 of the [update_batch] axis (replicas are
            # bit-identical by the training-side pmean discipline).
            loaded[prefix] = jax.tree.map(lambda x: x[0], placed)
        return self._bundle(loaded), found


def _flatten_with_prefix(template: Any, prefix: Tuple[str, ...]) -> Dict[Tuple[str, ...], Any]:
    from stoix_tpu.utils.checkpointing import _path_key

    return {
        prefix + _path_key(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(template)[0]
    }


class PolicyBundle(NamedTuple):
    """Everything the server needs to run a restored policy."""

    apply_fn: Callable[[Any, Any], Any]  # (params, batched observation) -> dist
    params: Any
    obs_template: Any  # ONE unbatched observation pytree
    step: int
    source: PolicySource
    train_config: Any


def resolve_train_config(config: Any) -> Any:
    """The TRAINING config the checkpoint was produced under (see module
    docstring for the precedence)."""
    serve_cfg = config.arch.serve
    ckpt_cfg = serve_cfg.checkpoint
    explicit = ckpt_cfg.get("train_config")
    if explicit:
        overrides = [str(o) for o in (ckpt_cfg.get("train_overrides") or [])]
        return config_lib.compose(
            config_lib.default_config_dir(), str(explicit), overrides
        )
    path = str(ckpt_cfg.path)
    if fleet.is_emergency_store(path):
        raise ValueError(
            "emergency stores carry no config metadata: set "
            "arch.serve.checkpoint.train_config to the training root yaml "
            "(e.g. default/anakin/default_ff_ppo.yaml) plus train_overrides"
        )
    meta = store_metadata(path)
    if not meta.get("env"):
        raise ValueError(
            f"store {path} has no usable config metadata; set "
            "arch.serve.checkpoint.train_config explicitly"
        )
    return config_lib.Config.from_dict(meta)


def load_policy(config: Any) -> PolicyBundle:
    """Build the servable policy for a composed serve config (the
    `default/serve.yaml` root): rebuild the actor from the training config,
    restore the actor-params subtree (+ observation statistics when the
    policy trained with normalize_observations), and return the bundle."""
    from stoix_tpu import envs
    from stoix_tpu.ops import running_statistics
    from stoix_tpu.systems.anakin import broadcast_to_update_batch

    serve_cfg = config.arch.serve
    ckpt_cfg = serve_cfg.checkpoint
    path = str(ckpt_cfg.path or "")
    if not path or path == "None":
        raise ValueError("arch.serve.checkpoint.path must name a checkpoint store")

    train_config = resolve_train_config(config)
    env, _ = envs.make(train_config)
    actor_network = build_actor(train_config, env)
    obs_template = env.observation_value()
    dummy_obs = jax.tree.map(lambda x: x[None], obs_template)
    init_params = actor_network.init(jax.random.PRNGKey(0), dummy_obs)
    update_batch = int(train_config.arch.get("update_batch_size", 1))

    params_path = str(ckpt_cfg.get("params_path") or DEFAULT_PARAMS_PATH)
    params_prefix = tuple(p for p in params_path.split("/") if p)
    templates: Dict[Tuple[str, ...], Any] = {
        params_prefix: broadcast_to_update_batch(init_params, update_batch)
    }

    normalize = bool(train_config.system.get("normalize_observations", False))
    stats_prefix = (OBS_STATS_PATH,)
    if normalize:
        stats_template = running_statistics.init_state(
            env.observation_value().agent_view
        )
        templates[stats_prefix] = broadcast_to_update_batch(
            stats_template, update_batch
        )

        def bundle(loaded: Dict[Tuple[str, ...], Any]) -> Any:
            return (loaded[params_prefix], loaded[stats_prefix])

        def apply_fn(bundled: Any, observation: Any) -> Any:
            actor_params, stats = bundled
            observation = running_statistics.normalize_observation(
                observation, stats
            )
            return actor_network.apply(actor_params, observation)

    else:

        def bundle(loaded: Dict[Tuple[str, ...], Any]) -> Any:
            return loaded[params_prefix]

        apply_fn = actor_network.apply

    source = PolicySource(path, templates, bundle)
    timestep = ckpt_cfg.get("timestep")
    params, step = source.load(None if timestep is None else int(timestep))
    scenario = train_config.env.scenario
    task = scenario.get("task_name", "policy") if hasattr(scenario, "get") else str(scenario)
    get_logger("stoix_tpu.serve").info(
        "[serve] restored %s policy at step %d from %s (%s store%s)",
        task, step, path,
        "emergency" if source.is_emergency else "orbax",
        ", obs-normalized" if normalize else "",
    )
    return PolicyBundle(
        apply_fn=apply_fn,
        params=params,
        obs_template=obs_template,
        step=step,
        source=source,
        train_config=train_config,
    )
