"""Latency-shaped load generation (docs/DESIGN.md §2.8).

OPEN-loop: requests are injected at the offered rate regardless of how fast
the server answers (closed-loop generators hide overload by self-throttling
— the coordinated-omission trap). Each request is an async `submit`; latency
is stamped inside the request future (enqueue -> result-ready), so the
generator thread never blocks on results and the offered rate holds.

The report is the serving bench's payload body: offered vs achieved QPS,
nearest-rank latency percentiles (the SAME nearest-rank definition as the
SLO telemetry window — one percentile semantics repo-wide), batch-fill
ratio, shed/error counts, and the hot-swap count over the window.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from stoix_tpu.serve.batcher import PendingRequest
from stoix_tpu.serve.client import BackoffPolicy, RetryBudgetExhaustedError, ServeClient
from stoix_tpu.utils.timing import TimingTracker

# The generator's default retry budget is deliberately TIGHT: an open-loop
# injector that sleeps a long backoff stops being open-loop (subsequent
# requests queue behind the sleep and then burst). Three quick jittered
# retries recover transient sheds; anything longer is counted shed and the
# schedule moves on.
DEFAULT_LOADGEN_RETRY = BackoffPolicy(
    base_s=0.002, max_s=0.020, multiplier=2.0, max_attempts=3, deadline_s=0.050
)


def run_loadgen(
    server: Any,  # PolicyServer
    offered_qps: float,
    duration_s: float,
    observation_fn: Optional[Callable[[int], Any]] = None,
    result_timeout_s: float = 30.0,
    retry_policy: Optional[BackoffPolicy] = None,
) -> Dict[str, Any]:
    """Drive `server` at `offered_qps` for `duration_s`; returns the latency
    report dict. `observation_fn(i)` supplies the i-th request's observation
    (default: the server's observation template every time). Sheds are
    retried through the backoff client (serve/client.py); a request is
    counted `shed` only once its whole retry budget is exhausted."""
    if offered_qps <= 0 or duration_s <= 0:
        raise ValueError("offered_qps and duration_s must be positive")
    if observation_fn is None:
        observation_fn = lambda _i: server.obs_template  # noqa: E731

    swaps_before = server.telemetry.n_hot_swaps
    batches_before = server.telemetry.n_batches
    client = ServeClient(server.submit, policy=retry_policy or DEFAULT_LOADGEN_RETRY)
    interval = 1.0 / float(offered_qps)
    requests: List[PendingRequest] = []
    shed = 0
    start = time.perf_counter()
    i = 0
    while True:
        now = time.perf_counter()
        if now - start >= duration_s:
            break
        target = start + i * interval
        if now < target:
            time.sleep(min(target - now, 0.010))
            continue
        try:
            requests.append(client.submit(observation_fn(i)))
        except RetryBudgetExhaustedError:
            shed += 1
        i += 1
    offered = i  # attempted submissions, shed included
    # QPS denominators use the INJECTION window only: the collect phase below
    # can wait up to result_timeout_s on a straggler, and folding that wait
    # into the denominator would let one slow request collapse the reported
    # rate (completed/32s instead of completed/2s).
    inject_elapsed = time.perf_counter() - start

    # Collect: every request either completes or times out (counted, never
    # hung — the generator must terminate even against a wedged server).
    deadline = time.perf_counter() + result_timeout_s
    timed_out = 0
    errors = 0
    tracker = TimingTracker(maxlen=max(1, len(requests)))
    for request in requests:
        remaining = deadline - time.perf_counter()
        if not request.wait(timeout=max(0.0, remaining)):
            timed_out += 1
            continue
        if request.ok:
            tracker.record("latency", request.latency_s)
        else:
            errors += 1
    completed = len(requests) - timed_out - errors
    percentiles = tracker.percentiles("latency")

    report: Dict[str, Any] = {
        "duration_s": round(inject_elapsed, 3),
        "offered_qps": round(offered / inject_elapsed, 2) if inject_elapsed > 0 else 0.0,
        "achieved_qps": round(completed / inject_elapsed, 2) if inject_elapsed > 0 else 0.0,
        "requests": offered,
        "completed": completed,
        "shed": shed,
        "retries": client.n_sheds - client.n_budget_exhausted,
        "errors": errors,
        "timed_out": timed_out,
        "latency_ms": {
            name: round(value * 1000.0, 3) for name, value in percentiles.items()
        },
        "batch_fill_ratio": round(server.telemetry.batch_fill_ratio(), 4),
        "batches": server.telemetry.n_batches - batches_before,
        "hot_swaps": server.telemetry.n_hot_swaps - swaps_before,
    }
    return report
