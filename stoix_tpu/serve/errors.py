"""Typed serving failures (docs/DESIGN.md §2.8).

The serving path's graceful-degradation contract: a server past its queue
bound SHEDS load with a typed, caller-distinguishable error instead of
letting the pending buffer grow without bound (queue growth is latency debt
every later request pays — shedding keeps the p99 of ACCEPTED requests
inside the SLO).
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base class for policy-serving failures (stoix_tpu/serve/)."""


class ServerOverloadError(ServeError):
    """The pending-request buffer is at its configured bound; this request
    was shed. Callers retry with backoff or surface the 429-equivalent."""

    def __init__(self, pending: int, bound: int):
        self.pending = int(pending)
        self.bound = int(bound)
        super().__init__(
            f"server overloaded: {pending} request(s) pending >= bound "
            f"{bound} — request shed (retry with backoff)"
        )


class ServerClosedError(ServeError):
    """Submit after shutdown, or a request dropped by server teardown."""

    def __init__(self, detail: str = "server is closed"):
        super().__init__(detail)


class RequestTimeoutError(ServeError):
    """A caller's result() wait expired before the batch completed."""

    def __init__(self, timeout_s: float):
        self.timeout_s = float(timeout_s)
        super().__init__(
            f"inference result not ready within {timeout_s:.1f}s"
        )
