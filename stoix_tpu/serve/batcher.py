"""Dynamic request batching (docs/DESIGN.md §2.8).

The TorchBeast idiom (arxiv 1910.03552 §3.1): concurrent callers enqueue
single observations; a worker coalesces whatever is pending into ONE padded
device batch. The two knobs:

  * `max_wait_s` — how long the oldest pending request may be held open
    waiting for company. 0 = flush immediately (latency-optimal, batch of
    whatever arrived during the previous device step); larger values trade
    first-request latency for occupancy.
  * bucket sizes — pending requests are padded UP to a fixed bucket
    (1, 2, 4, ... by default), so the jitted forward pass only ever sees
    len(buckets) distinct shapes: batch-size changes never recompile
    (STX012; pinned by the engine's compile-count probe in test_serve.py).

Backpressure is a BOUND, not a blocking put: `submit` past `max_queue`
raises the typed ServerOverloadError (docs/DESIGN.md §2.8 graceful
degradation) — an unbounded queue converts overload into unbounded latency
for every later caller.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional, Sequence

from stoix_tpu.serve.errors import (
    RequestTimeoutError,
    ServerClosedError,
    ServerOverloadError,
)

DEFAULT_BUCKETS = (1, 2, 4, 8, 16, 32)


def normalize_buckets(buckets: Sequence[int]) -> tuple:
    """Sorted, deduplicated, validated bucket ladder — the ONE definition
    shared by DynamicBatcher and InferenceEngine (both are built from the
    same config list; duplicated normalization drifts)."""
    cleaned = sorted({int(b) for b in buckets})
    if not cleaned or cleaned[0] < 1:
        raise ValueError(f"buckets must be positive ints, got {buckets!r}")
    return tuple(cleaned)


def bucket_for(buckets: Sequence[int], n: int) -> int:
    """Smallest bucket >= n (requests are padded up to it)."""
    for bucket in buckets:
        if n <= bucket:
            return bucket
    raise ValueError(f"batch of {n} exceeds the largest bucket {buckets[-1]}")


class PendingRequest:
    """One in-flight inference request: the caller's future."""

    __slots__ = ("observation", "enqueue_t", "done_t", "_event", "_result", "_error")

    def __init__(self, observation: Any):
        self.observation = observation
        self.enqueue_t = time.perf_counter()
        self.done_t: Optional[float] = None
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None

    # -- worker side ----------------------------------------------------------
    def set_result(self, result: Any) -> None:
        self.done_t = time.perf_counter()
        self._result = result
        self._event.set()

    def set_error(self, error: BaseException) -> None:
        self.done_t = time.perf_counter()
        self._error = error
        self._event.set()

    # -- caller side ----------------------------------------------------------
    def done(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float = 30.0) -> bool:
        return self._event.wait(timeout=timeout)

    def result(self, timeout: float = 30.0) -> Any:
        if not self._event.wait(timeout=timeout):
            raise RequestTimeoutError(timeout)
        if self._error is not None:
            raise self._error
        return self._result

    @property
    def ok(self) -> bool:
        return self._event.is_set() and self._error is None

    @property
    def latency_s(self) -> float:
        """Enqueue-to-result wall time (0.0 while still in flight)."""
        if self.done_t is None:
            return 0.0
        return self.done_t - self.enqueue_t


class DynamicBatcher:
    """Bounded pending buffer + deadline-driven batch formation."""

    def __init__(
        self,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        max_wait_s: float = 0.005,
        max_queue: int = 256,
    ):
        self.buckets = normalize_buckets(buckets)
        self.max_batch = self.buckets[-1]
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        if self.max_queue < self.max_batch:
            raise ValueError(
                f"max_queue ({self.max_queue}) must be >= the largest bucket "
                f"({self.max_batch}) or full batches could never form"
            )
        self._pending: deque = deque()
        self._cond = threading.Condition()
        self._closed = False

    def bucket_for(self, n: int) -> int:
        """Smallest configured bucket >= n (requests are padded up to it)."""
        return bucket_for(self.buckets, n)

    def depth(self) -> int:
        with self._cond:
            return len(self._pending)

    # -- caller side ----------------------------------------------------------
    def submit(self, observation: Any) -> PendingRequest:
        """Enqueue one observation; raises ServerOverloadError at the bound
        (the request is SHED — never silently queued past it) and
        ServerClosedError after close()."""
        request = PendingRequest(observation)
        with self._cond:
            if self._closed:
                raise ServerClosedError()
            if len(self._pending) >= self.max_queue:
                raise ServerOverloadError(len(self._pending), self.max_queue)
            self._pending.append(request)
            self._cond.notify()
        return request

    # -- worker side ----------------------------------------------------------
    def next_batch(self, idle_timeout: float = 0.1) -> List[PendingRequest]:
        """Dequeue the next batch (worker thread).

        Blocks up to `idle_timeout` for the FIRST request ([] on timeout, so
        the worker can poll its lifetime). Once one request is pending, the
        batch is held open until either the largest bucket is full or the
        OLDEST request has waited `max_wait_s` — the deadline is anchored to
        the oldest enqueue time, so no request's batching delay can exceed
        max_wait_s regardless of arrival pattern."""
        with self._cond:
            if not self._pending:
                if self._closed:
                    return []
                self._cond.wait(timeout=idle_timeout)
                if not self._pending:
                    return []
            while not self._closed and len(self._pending) < self.max_batch:
                oldest = self._pending[0]
                remaining = self.max_wait_s - (time.perf_counter() - oldest.enqueue_t)
                if remaining <= 0.0:
                    break
                self._cond.wait(timeout=remaining)
            n = min(len(self._pending), self.max_batch)
            return [self._pending.popleft() for _ in range(n)]

    def close(self, drain_error: Optional[BaseException] = None) -> int:
        """Stop accepting work and fail whatever is still pending with
        `drain_error` (default ServerClosedError) — a dropped request must
        never leave its caller blocked until result() times out. Returns the
        number of drained requests."""
        error = drain_error if drain_error is not None else ServerClosedError(
            "server shut down before this request was batched"
        )
        with self._cond:
            self._closed = True
            drained = list(self._pending)
            self._pending.clear()
            self._cond.notify_all()
        for request in drained:
            request.set_error(error)
        return len(drained)
