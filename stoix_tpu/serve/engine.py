"""Jitted policy forward pass for serving (docs/DESIGN.md §2.8).

One `jax.jit`-wrapped function built ONCE at engine construction (never in a
loop — STX012); each configured bucket size is one shape specialization of
it, compiled up front by `warmup()` so no live request ever pays a compile.
The trace-time `compile_count` probe makes the no-recompile property
TESTABLE: tracing the wrapped function is the only way the count moves, so
steady-state traffic across arbitrary batch sizes must leave it at
len(buckets) (pinned in tests/test_serve.py).

Parameter hot-swap discipline (same as Sebulba's ParameterServer.reprime):
fresh params are device_put OFF the request path, then installed with one
atomic reference assignment. The worker reads the reference once per batch —
an in-flight forward pass keeps the params it started with; no request ever
sees a torn mix of two versions.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from stoix_tpu.serve.batcher import DEFAULT_BUCKETS, bucket_for, normalize_buckets


class InferenceEngine:
    """Bucket-padded jitted `apply` over a hot-swappable params reference."""

    def __init__(
        self,
        apply_fn: Callable[[Any, Any], Any],
        params: Any,
        obs_template: Any,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        greedy: bool = True,
        key: Optional[jax.Array] = None,
    ):
        self.buckets = normalize_buckets(buckets)
        self._obs_template = obs_template
        self._params = jax.device_put(params)
        self._params_version = 0
        self._swap_lock = threading.Lock()
        self._base_key = key if key is not None else jax.random.PRNGKey(0)
        self._batch_index = 0
        self._trace_count = 0

        def _forward(p: Any, observation: Any, sample_key: jax.Array):
            # Trace-time side effect: this line runs ONCE per (shape, dtype)
            # specialization, which is exactly what the no-recompile tests
            # need to observe. It is not device code and costs nothing at
            # execution time.
            self._trace_count += 1
            dist = apply_fn(p, observation)
            action = dist.mode() if greedy else dist.sample(seed=sample_key)
            extras = {}
            logits = getattr(dist, "logits", None)
            if logits is not None:
                extras["logits"] = logits
            return action, extras

        self._step = jax.jit(_forward)

    # -- params ---------------------------------------------------------------
    @property
    def params_version(self) -> int:
        return self._params_version

    def set_params(self, params: Any) -> int:
        """Install fresh params under the in-flight jitted step: device_put
        first (the expensive part, off the request path), then ONE reference
        assignment. Returns the new version number."""
        local = jax.device_put(params)
        with self._swap_lock:
            self._params = local
            self._params_version += 1
            return self._params_version

    # -- inference ------------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct jit specializations traced so far (the recompile probe)."""
        return self._trace_count

    def bucket_for(self, n: int) -> int:
        return bucket_for(self.buckets, n)

    def batch_observations(self, observations: List[Any], bucket: int) -> Any:
        """Stack single-observation pytrees into one [bucket, ...] batch,
        padding the tail by repeating the last observation (pad rows ride the
        same forward pass and are sliced off the outputs)."""
        pad = bucket - len(observations)
        return jax.tree.map(
            lambda *leaves: np.stack(
                [np.asarray(leaf) for leaf in leaves]
                + [np.asarray(leaves[-1])] * pad
            ),
            *observations,
        )

    def infer(self, observations: List[Any]) -> Tuple[Any, Any, int]:
        """Run one padded batch; returns (action, extras, bucket) with
        leading dim `bucket` — the caller slices [:len(observations)]."""
        n = len(observations)
        bucket = self.bucket_for(n)
        batched = self.batch_observations(observations, bucket)
        sample_key = jax.random.fold_in(self._base_key, self._batch_index)
        self._batch_index += 1
        params = self._params  # ONE read: the whole batch sees one version
        action, extras = self._step(params, batched, sample_key)
        return action, extras, bucket

    def warmup(self) -> int:
        """Compile every bucket specialization up front (call under the
        server's first-compile watchdog). Returns the compile count."""
        for bucket in self.buckets:
            action, extras, _ = self.infer([self._obs_template] * bucket)
            jax.block_until_ready((action, extras))
        return self._trace_count
