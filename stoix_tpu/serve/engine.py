"""Jitted policy forward pass for serving (docs/DESIGN.md §2.8).

One `jax.jit`-wrapped function built ONCE at engine construction (never in a
loop — STX012); each configured bucket size is one shape specialization of
it, compiled up front by `warmup()` so no live request ever pays a compile.
The trace-time `compile_count` probe makes the no-recompile property
TESTABLE: tracing the wrapped function is the only way the count moves, so
steady-state traffic across arbitrary batch sizes must leave it at
len(buckets) (pinned in tests/test_serve.py).

Parameter hot-swap discipline (same as Sebulba's ParameterServer.reprime):
fresh params are device_put OFF the request path, then installed with one
atomic reference assignment. The worker reads the reference once per batch —
an in-flight forward pass keeps the params it started with; no request ever
sees a torn mix of two versions.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from stoix_tpu.serve.batcher import DEFAULT_BUCKETS, bucket_for, normalize_buckets


class InferenceEngine:
    """Bucket-padded jitted `apply` over a hot-swappable params reference."""

    def __init__(
        self,
        apply_fn: Callable[[Any, Any], Any],
        params: Any,
        obs_template: Any,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        greedy: bool = True,
        key: Optional[jax.Array] = None,
        device: Optional[jax.Device] = None,
    ):
        self.buckets = normalize_buckets(buckets)
        self._obs_template = obs_template
        # The serving device comes from the mesh-role abstraction
        # (parallel/roles.py `serve` role) when the server is built from
        # config; None keeps jax's default device — identical placement,
        # since the default serve role is device 0.
        self._device = device
        self._params = jax.device_put(params, device)
        self._params_version = 0
        self._swap_lock = threading.Lock()
        self._base_key = key if key is not None else jax.random.PRNGKey(0)
        self._batch_index = 0
        self._trace_count = 0

        def _forward(p: Any, observation: Any, sample_key: jax.Array):
            # Trace-time side effect: this line runs ONCE per (shape, dtype)
            # specialization, which is exactly what the no-recompile tests
            # need to observe. It is not device code and costs nothing at
            # execution time.
            self._trace_count += 1
            dist = apply_fn(p, observation)
            action = dist.mode() if greedy else dist.sample(seed=sample_key)
            extras = {}
            logits = getattr(dist, "logits", None)
            if logits is not None:
                extras["logits"] = logits
            return action, extras

        self._step = jax.jit(_forward)

    # -- params ---------------------------------------------------------------
    @property
    def params_version(self) -> int:
        return self._params_version

    def get_params(self) -> Any:
        """The currently-installed (device-resident) params reference. The
        fleet publisher captures this before a push so a canary-rejected
        rollout can roll every replica back to the exact pre-push bytes
        (docs/DESIGN.md §2.15) — read under the swap lock so a capture racing
        a swap still returns one coherent version."""
        with self._swap_lock:
            return self._params

    def set_params(self, params: Any) -> int:
        """Install fresh params under the in-flight jitted step: device_put
        first (the expensive part, off the request path), then ONE reference
        assignment. Returns the new version number."""
        local = jax.device_put(params, self._device)
        with self._swap_lock:
            self._params = local
            self._params_version += 1
            return self._params_version

    # -- inference ------------------------------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct jit specializations traced so far (the recompile probe)."""
        return self._trace_count

    def bucket_for(self, n: int) -> int:
        return bucket_for(self.buckets, n)

    def batch_observations(self, observations: List[Any], bucket: int) -> Any:
        """Stack single-observation pytrees into one [bucket, ...] batch,
        padding the tail by repeating the last observation (pad rows ride the
        same forward pass and are sliced off the outputs)."""
        pad = bucket - len(observations)
        return jax.tree.map(
            lambda *leaves: np.stack(
                [np.asarray(leaf) for leaf in leaves]
                + [np.asarray(leaves[-1])] * pad
            ),
            *observations,
        )

    def infer(self, observations: List[Any]) -> Tuple[Any, Any, int]:
        """Run one padded batch; returns (action, extras, bucket) with
        leading dim `bucket` — the caller slices [:len(observations)]."""
        n = len(observations)
        bucket = self.bucket_for(n)
        batched = self.batch_observations(observations, bucket)
        sample_key = jax.random.fold_in(self._base_key, self._batch_index)
        self._batch_index += 1
        params = self._params  # ONE read: the whole batch sees one version
        action, extras = self._step(params, batched, sample_key)
        return action, extras, bucket

    def warmup(self) -> int:
        """Compile every bucket specialization up front (call under the
        server's first-compile watchdog). Returns the compile count."""
        for bucket in self.buckets:
            action, extras, _ = self.infer([self._obs_template] * bucket)
            jax.block_until_ready((action, extras))
        return self._trace_count

    def canary(self, params: Any) -> Optional[str]:
        """Validate a hot-swap CANDIDATE without installing it; returns None
        when it passes, else a reason string. See validate_candidate."""
        return self.validate_candidate(params)[0]

    def validate_candidate(self, params: Any) -> Tuple[Optional[str], Any]:
        """The hot-swap canary (docs/DESIGN.md §2.9): every float parameter
        leaf must be finite, and a golden-input forward pass (the obs
        template through the smallest bucket — an already-compiled
        specialization, so the no-recompile pin holds across canaries) must
        produce finite outputs. Returns (reason, local): reason is None on
        pass, and `local` is the candidate ALREADY transferred to device —
        hand it straight to set_params so an accepted swap pays the
        host->device transfer once, not twice. The sample key is fixed: the
        canary must be deterministic, and it must not advance the serving
        batch counter."""
        bad = _first_nonfinite_leaf(params)
        if bad is not None:
            return f"candidate params carry non-finite values at {bad}", None
        bucket = self.buckets[0]
        batched = self.batch_observations([self._obs_template] * bucket, bucket)
        local = jax.device_put(params, self._device)
        try:
            action, extras = self._step(local, batched, self._base_key)
            outputs = jax.tree.map(np.asarray, (action, extras))
        except Exception as exc:  # noqa: BLE001 — a candidate that cannot even
            # run the forward pass (shape/dtype drift) must be rejected, not
            # crash the watcher thread.
            return f"golden forward pass failed: {type(exc).__name__}: {exc}", None
        bad = _first_nonfinite_leaf(outputs)
        if bad is not None:
            return f"golden forward pass produced non-finite outputs at {bad}", None
        return None, local


def _first_nonfinite_leaf(tree: Any) -> Optional[str]:
    """Tree-path of the first float leaf carrying NaN/inf, or None. Narrow
    floats (bfloat16) widen to float32 for the check, mirroring the
    checkpoint validator's discipline."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = np.asarray(leaf)
        if not jax.numpy.issubdtype(arr.dtype, jax.numpy.floating):
            continue
        if arr.dtype not in (np.float16, np.float32, np.float64):
            arr = arr.astype(np.float32)
        if not np.isfinite(arr).all():
            return jax.tree_util.keystr(path)
    return None
