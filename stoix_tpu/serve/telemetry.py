"""SLO telemetry for the serving path (docs/DESIGN.md §2.8).

Two layers, one set of increments:

  * the process-wide metrics registry (`stoix_tpu_serve_*` in the
    `stoix_tpu_<area>_<name>` convention, docs/DESIGN.md §2.2) — Prometheus
    text exposition + JSONL via the existing exporters, so a scraper sees
    serving traffic next to training telemetry;
  * per-server local counters and a rolling TimingTracker window — the
    precise nearest-rank p50/p95/p99 snapshot an SLO check or the load
    generator reads without decoding Prometheus buckets (and without being
    polluted by a previous server in the same process).

All instruments are host-memory only: recording never touches a device.
"""

from __future__ import annotations

import threading
from typing import Dict

from stoix_tpu.observability import get_registry, write_prometheus
from stoix_tpu.utils.timing import TimingTracker

# Request latencies are ms-scale, not host-loop-phase scale: resolve the
# sub-100ms region the default phase buckets lump together.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 10.0,
)


class ServeTelemetry:
    """One server's SLO instruments; registry series are shared process-wide
    (get-or-create), the local snapshot state is per-instance."""

    def __init__(self, window: int = 4096):
        registry = get_registry()
        self._requests = registry.counter(
            "stoix_tpu_serve_requests_total",
            "Inference requests by outcome (ok|shed|error)",
        )
        self._queue_depth = registry.gauge(
            "stoix_tpu_serve_queue_depth",
            "Requests currently buffered in the dynamic batcher",
        )
        self._occupancy = registry.gauge(
            "stoix_tpu_serve_batch_occupancy",
            "Fill ratio (valid/bucket) of the most recent inference batch",
        )
        self._fill = registry.histogram(
            "stoix_tpu_serve_batch_fill_ratio",
            "Fill ratio (valid/bucket) per inference batch",
            buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0),
        )
        self._request_latency = registry.histogram(
            "stoix_tpu_serve_request_latency_seconds",
            "End-to-end latency per request (enqueue -> result ready)",
            buckets=LATENCY_BUCKETS,
        )
        self._batch_latency = registry.histogram(
            "stoix_tpu_serve_batch_latency_seconds",
            "Device forward-pass wall time per batch (incl. host transfer)",
            buckets=LATENCY_BUCKETS,
        )
        self._hot_swaps = registry.counter(
            "stoix_tpu_serve_hot_swaps_total",
            "Parameter hot-swaps applied by the checkpoint watcher",
        )
        self._swap_errors = registry.counter(
            "stoix_tpu_serve_hot_swap_errors_total",
            "Checkpoint-watcher polls that failed (server keeps old params)",
        )
        self._lock = threading.Lock()
        self._tracker = TimingTracker(maxlen=window)
        # Local mirrors: per-server values for slo_snapshot() (registry
        # counters are process-cumulative across servers/tests).
        self.n_ok = 0
        self.n_shed = 0
        self.n_error = 0
        self.n_batches = 0
        self.n_hot_swaps = 0
        self._fill_sum = 0.0

    # -- recording ------------------------------------------------------------
    def queue_depth(self, depth: int) -> None:
        self._queue_depth.set(float(depth))

    def request_ok(self, latency_s: float) -> None:
        self._requests.inc(labels={"outcome": "ok"})
        self._request_latency.observe(latency_s)
        with self._lock:
            self.n_ok += 1
            self._tracker.record("request_latency", latency_s)

    def request_shed(self) -> None:
        self._requests.inc(labels={"outcome": "shed"})
        with self._lock:
            self.n_shed += 1

    def request_error(self, n: int = 1) -> None:
        self._requests.inc(float(n), labels={"outcome": "error"})
        with self._lock:
            self.n_error += int(n)

    def batch_done(self, valid: int, bucket: int, latency_s: float) -> None:
        ratio = float(valid) / float(bucket)
        self._occupancy.set(ratio)
        self._fill.observe(ratio)
        self._batch_latency.observe(latency_s)
        with self._lock:
            self.n_batches += 1
            self._fill_sum += ratio

    def hot_swap(self) -> None:
        self._hot_swaps.inc()
        with self._lock:
            self.n_hot_swaps += 1

    def hot_swap_error(self) -> None:
        self._swap_errors.inc()

    # -- reading --------------------------------------------------------------
    def latency_percentiles_ms(self) -> Dict[str, float]:
        """Nearest-rank p50/p95/p99/max (ms) over the rolling request window
        ({} before the first completed request)."""
        with self._lock:
            stats = self._tracker.percentiles("request_latency")
        return {k: v * 1000.0 for k, v in stats.items()}

    def batch_fill_ratio(self) -> float:
        """Mean fill ratio over every batch this server ran (0.0 when idle)."""
        with self._lock:
            return self._fill_sum / self.n_batches if self.n_batches else 0.0

    def slo_snapshot(self) -> Dict[str, float]:
        """The SLO dashboard dict: request outcomes, latency percentiles
        (ms), batch occupancy, hot-swap count."""
        snap: Dict[str, float] = {
            "requests_ok": self.n_ok,
            "requests_shed": self.n_shed,
            "requests_error": self.n_error,
            "batches": self.n_batches,
            "batch_fill_ratio": round(self.batch_fill_ratio(), 4),
            "hot_swaps": self.n_hot_swaps,
        }
        for name, value in self.latency_percentiles_ms().items():
            snap[f"latency_ms_{name}"] = round(value, 3)
        return snap

    def export(self, directory: str) -> str:
        """Write the registry's Prometheus text snapshot (serving series
        included) under `directory`; returns the file path."""
        import os

        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "serve_metrics.prom")
        write_prometheus(path)
        return path
