"""PolicyServer: the serving subsystem's composition root (docs/DESIGN.md
§2.8).

Wires checkpoint loading (serve/checkpoint.py), the dynamic batcher
(serve/batcher.py), the jitted engine (serve/engine.py), SLO telemetry
(serve/telemetry.py), and the hot-swap watcher (serve/hotswap.py) into one
lifecycle:

    server = PolicyServer.from_config(compose(dir, "default/serve.yaml", ov))
    with server:                      # start(): watchdog-guarded warmup
        result = server.infer(obs)    # or submit() for async callers

One worker thread owns the device: it drains the batcher, pads to a bucket,
runs the jitted forward pass, and completes each request's future. Caller
threads never touch jax — submit/result are pure host-side queue operations,
so ANY number of concurrent callers share the one engine.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import numpy as np

from stoix_tpu.observability import (
    get_health_monitor,
    get_logger,
    get_status_board,
    span,
)
from stoix_tpu.parallel import MeshRoles
from stoix_tpu.resilience import faultinject
from stoix_tpu.serve import checkpoint as serve_checkpoint
from stoix_tpu.serve.batcher import DEFAULT_BUCKETS, DynamicBatcher, PendingRequest
from stoix_tpu.serve.engine import InferenceEngine
from stoix_tpu.serve.errors import ServerClosedError, ServerOverloadError
from stoix_tpu.serve.hotswap import ParameterWatcher
from stoix_tpu.serve.telemetry import ServeTelemetry


class ServeResult(NamedTuple):
    """One request's answer: the action plus distribution extras (logits for
    categorical heads) as host numpy arrays."""

    action: np.ndarray
    extras: Dict[str, np.ndarray]


class PolicyServer:
    def __init__(
        self,
        apply_fn: Any,
        params: Any,
        obs_template: Any,
        buckets: Any = DEFAULT_BUCKETS,
        max_wait_s: float = 0.005,
        max_queue: int = 256,
        greedy: bool = True,
        key: Optional[jax.Array] = None,
        source: Any = None,
        initial_step: int = 0,
        hot_swap_poll_s: float = 0.0,
        hot_swap_canary: bool = True,
        compile_deadline_s: float = 600.0,
        device: Optional[jax.Device] = None,
        name: str = "serve",
        replica_id: Optional[int] = None,
    ):
        # `name` namespaces the global status/health registrations so N
        # replicas can coexist in one process (the loop fleet,
        # docs/DESIGN.md §2.15). The default reproduces the original keys
        # ("serve_slo" / "serve-worker") exactly, so the single-server path
        # registers bit-identically to before. `replica_id` is the fleet
        # ordinal — only the replica_slow fault injection reads it.
        self.name = str(name)
        self._replica_id = replica_id
        self.telemetry = ServeTelemetry()
        self.obs_template = obs_template
        self._engine = InferenceEngine(
            apply_fn, params, obs_template, buckets=buckets, greedy=greedy, key=key,
            device=device,
        )
        self._batcher = DynamicBatcher(
            buckets=buckets, max_wait_s=max_wait_s, max_queue=max_queue
        )
        self._compile_deadline_s = float(compile_deadline_s)
        self._stop = threading.Event()
        self._killed = threading.Event()
        self._worker = threading.Thread(
            target=self._worker_loop, name=self._worker_name(), daemon=True
        )
        self._started = False
        self._log = get_logger("stoix_tpu.serve")
        self.watcher: Optional[ParameterWatcher] = None
        if source is not None and hot_swap_poll_s > 0:
            self.watcher = ParameterWatcher(
                source,
                self._engine,
                self.telemetry,
                current_step=initial_step,
                poll_interval_s=hot_swap_poll_s,
                canary=hot_swap_canary,
            )

    @classmethod
    def from_config(cls, config: Any, roles: Optional[MeshRoles] = None) -> "PolicyServer":
        """Build from a composed serve config (the `default/serve.yaml` root
        with the configs/arch/serve.yaml block under config.arch.serve).

        Device assignment rides the unified mesh-role abstraction
        (parallel/roles.py, docs/DESIGN.md §2.11): the `serve` role names the
        device the engine owns (default: device 0 — jax's default device,
        i.e. the pre-MeshRoles placement). Pass `roles` to share one
        MeshRoles object across subsystems (e.g. a colocated train+serve
        deployment)."""
        bundle = serve_checkpoint.load_policy(config)
        serve_cfg = config.arch.serve
        if roles is None:
            roles = MeshRoles.from_config(config)
        batching = serve_cfg.batching
        hot_swap = serve_cfg.hot_swap
        seed = int(serve_cfg.get("seed", 0))
        return cls(
            apply_fn=bundle.apply_fn,
            params=bundle.params,
            obs_template=bundle.obs_template,
            buckets=[int(b) for b in batching.buckets],
            max_wait_s=float(batching.max_wait_ms) / 1000.0,
            max_queue=int(batching.max_queue),
            greedy=bool(serve_cfg.greedy),
            key=jax.random.PRNGKey(seed),
            source=bundle.source,
            initial_step=bundle.step,
            hot_swap_poll_s=(
                float(hot_swap.poll_interval_s) if bool(hot_swap.enabled) else 0.0
            ),
            hot_swap_canary=bool(hot_swap.get("canary", True)),
            compile_deadline_s=float(serve_cfg.compile_deadline_s),
            device=roles.device("serve"),
        )

    # -- naming ---------------------------------------------------------------
    def _worker_name(self) -> str:
        # "serve" -> "serve-worker" (the historical thread/check name);
        # "loop_replica0" -> "loop_replica0-worker".
        return f"{self.name}-worker"

    def _status_key(self) -> str:
        return f"{self.name}_slo"

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "PolicyServer":
        """Warm every bucket under a first-compile watchdog (a wedged backend
        raises CompileStallError with a stack dump instead of hanging the
        server forever — docs/DESIGN.md §2.4 discipline), then start the
        worker and the hot-swap watcher."""
        if self._started:
            return self
        from stoix_tpu.resilience.watchdog import Watchdog

        with Watchdog("serve_warmup", deadline_s=self._compile_deadline_s):
            compiled = self._engine.warmup()
        self._log.info(
            "[serve] warmed %d bucket specialization(s) %s — serving",
            compiled, list(self._engine.buckets),
        )
        self._worker.start()
        if self.watcher is not None:
            self.watcher.start()
        # Ops plane (docs/DESIGN.md §2.13): /statusz renders the SLO ladder
        # live (the provider is called at render time, not snapshotted here)
        # and /healthz turns 503 if the batch worker thread dies.
        get_status_board().register_provider(
            self._status_key(), self.telemetry.slo_snapshot
        )
        get_health_monitor().register_check(
            self._worker_name(),
            lambda: None if self._worker.is_alive() else "serve worker thread dead",
        )
        self._started = True
        return self

    def close(self, join_timeout: float = 10.0) -> None:
        get_status_board().unregister_provider(self._status_key())
        get_health_monitor().unregister(self._worker_name())
        if self.watcher is not None:
            self.watcher.stop()
        self._stop.set()
        if self._worker.is_alive():
            self._worker.join(timeout=join_timeout)
        dropped = self._batcher.close()
        if dropped:
            self._log.warning(
                "[serve] shutdown dropped %d still-pending request(s) "
                "(completed with ServerClosedError)", dropped,
            )

    def kill(self, join_timeout: float = 10.0) -> None:
        """Crash-style shutdown (the `replica_kill` chaos drill,
        docs/DESIGN.md §2.15). Unlike close()'s graceful drain, the worker
        dies WITHOUT completing its current batch: every queued and in-batch
        request completes with ServerClosedError — exactly what a powered-off
        replica looks like to the FleetRouter, whose failover path must
        re-dispatch the accepted requests."""
        self._killed.set()
        self.close(join_timeout=join_timeout)

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, *exc_info: Any) -> None:
        self.close()

    # -- request path ---------------------------------------------------------
    @property
    def engine(self) -> InferenceEngine:
        """The replica's engine — the fleet publisher drives check_now /
        rollback against it (docs/DESIGN.md §2.15)."""
        return self._engine

    def healthy(self) -> bool:
        """Liveness probe the FleetRouter polls for ejection/re-admission:
        started, not closing, and the batch worker thread still running."""
        return self._started and not self._stop.is_set() and self._worker.is_alive()

    @property
    def compile_count(self) -> int:
        return self._engine.compile_count

    @property
    def params_version(self) -> int:
        return self._engine.params_version

    def submit(self, observation: Any) -> PendingRequest:
        """Async path: enqueue one unbatched observation pytree (shaped like
        `obs_template`); returns the request future. Raises
        ServerOverloadError when shedding and ServerClosedError after
        close() — both typed, both counted."""
        if not self._started:
            raise ServerClosedError("server not started — call start() first")
        try:
            request = self._batcher.submit(observation)
        except ServerOverloadError:
            self.telemetry.request_shed()
            raise
        self.telemetry.queue_depth(self._batcher.depth())
        return request

    def infer(self, observation: Any, timeout: float = 30.0) -> ServeResult:
        """Sync convenience: submit + wait."""
        return self.submit(observation).result(timeout=timeout)

    # -- worker ---------------------------------------------------------------
    def _complete(self, batch: List[PendingRequest], action: Any, extras: Any) -> None:
        action_np = np.asarray(action)
        extras_np = {k: np.asarray(v) for k, v in extras.items()}
        for i, request in enumerate(batch):
            request.set_result(
                ServeResult(
                    action=action_np[i],
                    extras={k: v[i] for k, v in extras_np.items()},
                )
            )
            self.telemetry.request_ok(request.latency_s)

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            batch = self._batcher.next_batch(idle_timeout=0.05)
            if not batch:
                continue
            try:
                if self._replica_id is not None:
                    faultinject.maybe_slow_replica(self._replica_id)
                with span("serve_batch", n=len(batch)):
                    start = time.perf_counter()
                    action, extras, bucket = self._engine.infer(
                        [request.observation for request in batch]
                    )
                    if self._killed.is_set():
                        # Crash-style kill(): the batch dies WITH the worker
                        # — callers see ServerClosedError and fail over.
                        raise ServerClosedError(f"{self.name} killed mid-batch")
                    self._complete(batch, action, extras)
                self.telemetry.batch_done(
                    len(batch), bucket, time.perf_counter() - start
                )
                self.telemetry.queue_depth(self._batcher.depth())
            except Exception as exc:  # noqa: BLE001 — one malformed
                # observation must fail ITS batch with a typed result, not
                # kill the worker and wedge every later caller.
                self.telemetry.request_error(len(batch))
                for request in batch:
                    request.set_error(exc)
                self._log.error(
                    "[serve] batch of %d failed: %s: %s",
                    len(batch), type(exc).__name__, exc,
                )
