"""Shed-aware submission client: bounded exponential backoff + full jitter
(docs/DESIGN.md §2.15).

`ServerOverloadError` has always told callers to "retry with backoff"; this
module is that retry, implemented once so every caller (the open-loop load
generator, the FleetRouter's per-replica submits) shares one schedule:

  * exponential growth `base * multiplier**attempt`, capped at `max_delay`;
  * FULL jitter — the actual sleep is uniform on [0, bounded] (decorrelated
    retries; synchronized clients re-colliding at the same instant is the
    classic thundering-herd failure the jitter exists to break);
  * a retry BUDGET — both an attempt cap and a wall-clock deadline. A caller
    that cannot get in within the budget receives the typed
    `RetryBudgetExhaustedError` naming both, with the final shed error
    chained as __cause__.

The sleep and RNG are injectable so the schedule itself is unit-testable
without wall-clock time (tests/test_loop.py pins the bounded-exponential
envelope and the budget exhaustion).
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, NamedTuple, Optional

from stoix_tpu.serve.errors import ServeError, ServerOverloadError


class BackoffPolicy(NamedTuple):
    """Bounded-exponential-backoff schedule + retry budget."""

    base_s: float = 0.002
    max_s: float = 0.100
    multiplier: float = 2.0
    max_attempts: int = 5
    deadline_s: float = 1.0

    def bound(self, attempt: int) -> float:
        """The jitter-free upper envelope for retry number `attempt` (0-based):
        min(max_s, base_s * multiplier**attempt)."""
        return min(float(self.max_s), float(self.base_s) * float(self.multiplier) ** attempt)


class RetryBudgetExhaustedError(ServeError):
    """Every attempt in the retry budget was shed. Names the budget that was
    spent (attempts + deadline) so operators can tell "server briefly busy"
    from "budget too small" at a glance."""

    def __init__(self, attempts: int, deadline_s: float, elapsed_s: float):
        self.attempts = int(attempts)
        self.deadline_s = float(deadline_s)
        self.elapsed_s = float(elapsed_s)
        super().__init__(
            f"retry budget exhausted: {attempts} attempt(s) all shed within "
            f"{elapsed_s:.3f}s (budget: {attempts} attempts / {deadline_s:.3f}s "
            f"deadline)"
        )


def backoff_delay(
    policy: BackoffPolicy, attempt: int, rng: random.Random
) -> float:
    """One full-jitter sample for retry number `attempt` (0-based): uniform
    on [0, policy.bound(attempt)]."""
    return rng.uniform(0.0, policy.bound(attempt))


class ServeClient:
    """Retrying wrapper around one submit target.

    `submit_fn` is anything with PolicyServer.submit semantics (raises
    ServerOverloadError on shed); `submit()` retries sheds per the policy and
    returns the accepted request future. All other errors (ServerClosedError
    included) pass straight through — a closed server is not a transient."""

    def __init__(
        self,
        submit_fn: Callable[[Any], Any],
        policy: Optional[BackoffPolicy] = None,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._submit = submit_fn
        self.policy = policy or BackoffPolicy()
        self._rng = rng if rng is not None else random.Random()
        self._sleep = sleep
        # Host-side mirrors (telemetry-style): total sheds seen vs retries
        # that eventually got in vs budgets exhausted.
        self.n_sheds = 0
        self.n_retried_ok = 0
        self.n_budget_exhausted = 0

    def submit(self, observation: Any) -> Any:
        start = time.monotonic()
        attempts = 0
        while True:
            try:
                request = self._submit(observation)
                if attempts:
                    self.n_retried_ok += 1
                return request
            except ServerOverloadError as exc:
                self.n_sheds += 1
                attempts += 1
                elapsed = time.monotonic() - start
                delay = backoff_delay(self.policy, attempts - 1, self._rng)
                if (
                    attempts >= self.policy.max_attempts
                    or elapsed + delay > self.policy.deadline_s
                ):
                    self.n_budget_exhausted += 1
                    raise RetryBudgetExhaustedError(
                        attempts, self.policy.deadline_s, elapsed
                    ) from exc
                self._sleep(delay)


def policy_from_config(retry_cfg: Any) -> BackoffPolicy:
    """Build a BackoffPolicy from a `retry:` config block (ms-denominated
    keys, matching the serve config's latency-unit convention); None/empty
    yields the defaults."""
    cfg = dict(retry_cfg or {})
    defaults = BackoffPolicy()
    return BackoffPolicy(
        base_s=float(cfg.get("base_ms", defaults.base_s * 1000.0)) / 1000.0,
        max_s=float(cfg.get("max_ms", defaults.max_s * 1000.0)) / 1000.0,
        multiplier=float(cfg.get("multiplier", defaults.multiplier)),
        max_attempts=int(cfg.get("max_attempts", defaults.max_attempts)),
        deadline_s=float(cfg.get("deadline_ms", defaults.deadline_s * 1000.0)) / 1000.0,
    )
