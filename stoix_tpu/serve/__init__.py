"""stoix_tpu.serve — dynamic-batching policy serving (docs/DESIGN.md §2.8).

Training is not the only traffic shape: this subsystem gives a trained
policy its production life. It composes pieces the repo already had —
Sebulba's inference discipline, PR 4's topology-elastic restore (any
checkpoint serves on any mesh), PR 2's metrics registry — into a second,
LATENCY-shaped traffic class:

  * `PolicyServer` — checkpoint in, concurrent `submit`/`infer` out; one
    worker thread owns the jitted forward pass.
  * `DynamicBatcher` — pending requests coalesce into padded fixed-bucket
    batches under a max-wait deadline (batch size never recompiles).
  * `InferenceEngine` — the jitted apply with atomic parameter hot-swap and
    a trace-count recompile probe.
  * `ParameterWatcher` — polls the checkpoint store; a live learner feeds a
    live server.
  * `ServeTelemetry` — `stoix_tpu_serve_*` SLO metrics (p50/p95/p99).
  * `run_loadgen` — open-loop latency-shaped load generation (bench.py
    --serve).
  * `ServeClient` — the shed-retry client (bounded exponential backoff +
    full jitter + a retry budget) shared by the load generator and the
    closed-loop FleetRouter (stoix_tpu/loop, docs/DESIGN.md §2.15).
"""

from stoix_tpu.serve.batcher import (  # noqa: F401 — public API
    DEFAULT_BUCKETS,
    DynamicBatcher,
    PendingRequest,
)
from stoix_tpu.serve.checkpoint import (  # noqa: F401 — public API
    PolicyBundle,
    PolicySource,
    load_policy,
)
from stoix_tpu.serve.client import (  # noqa: F401
    BackoffPolicy,
    RetryBudgetExhaustedError,
    ServeClient,
    backoff_delay,
)
from stoix_tpu.serve.engine import InferenceEngine  # noqa: F401
from stoix_tpu.serve.errors import (  # noqa: F401
    RequestTimeoutError,
    ServeError,
    ServerClosedError,
    ServerOverloadError,
)
from stoix_tpu.serve.hotswap import ParameterWatcher  # noqa: F401
from stoix_tpu.serve.loadgen import run_loadgen  # noqa: F401
from stoix_tpu.serve.server import PolicyServer, ServeResult  # noqa: F401
from stoix_tpu.serve.telemetry import ServeTelemetry  # noqa: F401
