"""Parameter hot-swap: a live learner feeds a live server (docs/DESIGN.md
§2.8).

A watcher thread polls the checkpoint store's step listing (a directory scan
— no leaf I/O) every `poll_interval_s`; when a NEWER step appears it loads
the actor subtree through the same PolicySource the server booted from and
installs it with the engine's atomic swap (device_put off the request path,
then one reference assignment — the ParameterServer.reprime discipline).
In-flight batches finish on the params they started with; requests batched
after the swap see the new version. A failed poll — half-written checkpoint,
transient I/O — is counted, logged, and SKIPPED: the server keeps serving
the params it has (orbax's atomic step-directory commit makes a torn read a
transient, not a corruption).
"""

from __future__ import annotations

import threading
from typing import Optional

from stoix_tpu.observability import get_logger
from stoix_tpu.serve.engine import InferenceEngine
from stoix_tpu.serve.telemetry import ServeTelemetry


class ParameterWatcher:
    """Background poll -> load -> atomic swap loop."""

    def __init__(
        self,
        source,  # serve.checkpoint.PolicySource
        engine: InferenceEngine,
        telemetry: ServeTelemetry,
        current_step: int,
        poll_interval_s: float = 2.0,
    ):
        self._source = source
        self._engine = engine
        self._telemetry = telemetry
        self.current_step = int(current_step)
        self.poll_interval_s = float(poll_interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serve-hotswap", daemon=True
        )
        self._log = get_logger("stoix_tpu.serve")

    def start(self) -> "ParameterWatcher":
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    def check_now(self) -> Optional[int]:
        """One synchronous poll (tests and deterministic swap points): swap
        if the store advanced; returns the new step, or None for no-op/error."""
        try:
            latest = self._source.latest_step()
            if latest is None or latest <= self.current_step:
                return None
            params, step = self._source.load(latest)
            version = self._engine.set_params(params)
            previous, self.current_step = self.current_step, step
            self._telemetry.hot_swap()
            self._log.info(
                "[serve] hot-swapped params: step %d -> %d (version %d)",
                previous, step, version,
            )
            return step
        except Exception as exc:  # noqa: BLE001 — a half-written checkpoint
            # or transient I/O error must not kill serving; keep the params
            # we have and retry next poll.
            self._telemetry.hot_swap_error()
            self._log.warning(
                "[serve] hot-swap poll failed (%s: %s) — serving step %d "
                "until the next poll", type(exc).__name__, exc, self.current_step,
            )
            return None

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.poll_interval_s):
            self.check_now()
