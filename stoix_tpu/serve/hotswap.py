"""Parameter hot-swap: a live learner feeds a live server (docs/DESIGN.md
§2.8, canary semantics §2.9).

A watcher thread polls the checkpoint store's step listing (a directory scan
— no leaf I/O) every `poll_interval_s`; when a NEWER step appears it loads
the actor subtree through the same PolicySource the server booted from,
validates it, and installs it with the engine's atomic swap (device_put off
the request path, then one reference assignment — the
ParameterServer.reprime discipline). In-flight batches finish on the params
they started with; requests batched after the swap see the new version.

Three gates stand between a fresh checkpoint and live traffic:

  * **digest verification** (PolicySource / fleet.read_emergency_raw): when
    the store carries a sha256 manifest, the loaded bytes must match it —
    bit-rot and half-synced stores are rejected at read time;
  * **the canary** (`InferenceEngine.canary`, on by default via
    `arch.serve.hot_swap.canary`): every float leaf of the candidate must be
    finite, and a golden-input forward pass through an already-compiled
    bucket specialization must produce finite outputs. A learner that
    diverged to NaN — or a store that restored garbage — keeps the OLD
    params serving; previously `ParameterWatcher` swapped in whatever
    restored.
  * **typed failure accounting**: a failed poll, digest mismatch, or canary
    rejection increments `stoix_tpu_serve_hot_swap_errors_total`, logs the
    reason, and is SKIPPED — the server keeps serving (orbax's atomic
    step-directory commit makes a torn read a transient, not a corruption).

`STOIX_TPU_FAULT=swap_poison` (resilience/faultinject.py) poisons exactly
one loaded candidate with NaN so the reject-and-keep-serving path is
provable end-to-end (tests/test_integrity.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from stoix_tpu.observability import get_logger
from stoix_tpu.resilience import faultinject
from stoix_tpu.serve.engine import InferenceEngine
from stoix_tpu.serve.telemetry import ServeTelemetry


class ParameterWatcher:
    """Background poll -> load -> canary -> atomic swap loop."""

    def __init__(
        self,
        source,  # serve.checkpoint.PolicySource
        engine: InferenceEngine,
        telemetry: ServeTelemetry,
        current_step: int,
        poll_interval_s: float = 2.0,
        canary: bool = True,
    ):
        self._source = source
        self._engine = engine
        self._telemetry = telemetry
        self.current_step = int(current_step)
        self.poll_interval_s = float(poll_interval_s)
        self.canary = bool(canary)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="serve-hotswap", daemon=True
        )
        self._log = get_logger("stoix_tpu.serve")

    def start(self) -> "ParameterWatcher":
        self._thread.start()
        return self

    def stop(self, join_timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout)

    def check_now(self, target_step: Optional[int] = None) -> Optional[int]:
        """One synchronous poll (tests and deterministic swap points): swap
        if the store advanced AND the candidate passes the canary; returns
        the new step, or None for no-op/rejected/error.

        `target_step` pins the candidate instead of re-resolving the store's
        latest — the FleetPublisher passes the step it gated on, so every
        replica in one fleet push loads the SAME step even while the learner
        is concurrently saving a newer one (two latest_step() scans racing a
        save can disagree, which would tear the fleet for no real fault)."""
        try:
            latest = (
                self._source.latest_step() if target_step is None else int(target_step)
            )
            if latest is None or latest <= self.current_step:
                return None
            params, step = self._source.load(latest)
            # Chaos (`swap_poison`, one-shot): hand the canary a non-finite
            # candidate — the class of restore the gate exists to stop.
            params = faultinject.maybe_poison_swap(params)
            if self.canary:
                reason, local = self._engine.validate_candidate(params)
                if reason is not None:
                    self._telemetry.hot_swap_error()
                    self._log.warning(
                        "[serve] hot-swap canary REJECTED step %d (%s) — "
                        "keeping step %d serving until the next poll",
                        step, reason, self.current_step,
                    )
                    return None
                # The canary already transferred the candidate to device;
                # installing `local` makes set_params' device_put a no-op.
                params = local
            version = self._engine.set_params(params)
            previous, self.current_step = self.current_step, step
            self._telemetry.hot_swap()
            self._log.info(
                "[serve] hot-swapped params: step %d -> %d (version %d)",
                previous, step, version,
            )
            return step
        except Exception as exc:  # noqa: BLE001 — a half-written checkpoint,
            # digest mismatch, or transient I/O error must not kill serving;
            # keep the params we have and retry next poll.
            self._telemetry.hot_swap_error()
            self._log.warning(
                "[serve] hot-swap poll failed (%s: %s) — serving step %d "
                "until the next poll", type(exc).__name__, exc, self.current_step,
            )
            return None

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.poll_interval_s):
            self.check_now()
