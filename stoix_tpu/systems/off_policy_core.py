"""Generic Anakin off-policy scaffolding for actor-critic systems
(DDPG/TD3/D4PG/SAC). Mirrors q_family.py's skeleton with an arbitrary params
pytree and a system-supplied per-shard learner.

Flow per update (reference ff_ddpg.py / ff_sac.py structure):
  scan(_env_step) rollout -> buffer.add -> scan(_update_epoch){ sample ->
  critic grad/update -> actor grad/update -> polyak targets } in one
  shard_mapped program; warmup pre-fills with uniform random actions.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OffPolicyLearnerState, Transition
from stoix_tpu.buffers import make_item_buffer
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.systems import anakin
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims


def make_transition(last_timestep: Any, action: jax.Array, timestep: Any) -> Transition:
    return Transition(
        obs=last_timestep.observation,
        action=action,
        reward=timestep.reward,
        done=timestep.discount == 0.0,
        next_obs=timestep.extras["next_obs"],
        info=timestep.extras["episode_metrics"],
    )


def dummy_transition(env: envs.Environment, discrete_actions: bool = False) -> Transition:
    return Transition(
        obs=env.observation_value(),
        action=jnp.asarray(env.action_value(), jnp.int32 if discrete_actions else jnp.float32),
        reward=jnp.zeros((), jnp.float32),
        done=jnp.zeros((), bool),
        next_obs=env.observation_value(),
        info={
            "episode_return": jnp.zeros((), jnp.float32),
            "episode_length": jnp.zeros((), jnp.int32),
            "is_terminal_step": jnp.zeros((), bool),
        },
    )


def build_buffer(env: envs.Environment, config: Any, mesh: Mesh, discrete_actions: bool = False):
    """Per-(shard, update-batch) replay, dispatched on `system.replay.impl`
    (docs/DESIGN.md §2.10):

      local (default)  today's replicated uniform item buffer — every shard
                       samples only its own slice; bit-identical to the
                       pre-dispatch behavior (tests/test_replay.py pins it).
      sharded          the device-resident cross-shard sampler
                       (stoix_tpu/replay): the same ItemBuffer interface,
                       but `sample` draws the GLOBAL batch where the data
                       lives — one all_gather of shard masses + one psum of
                       the sampled minibatch — so per-shard HBM bounds only
                       a SHARD of the experience, not all of it.
    """
    n_shards = int(mesh.shape["data"])
    update_batch = int(config.arch.get("update_batch_size", 1))
    local_envs = int(config.arch.total_num_envs) // (n_shards * update_batch)
    buffer_size = max(1, int(config.system.total_buffer_size) // (n_shards * update_batch))
    batch_size = max(1, int(config.system.total_batch_size) // (n_shards * update_batch))
    replay_cfg = dict(config.system.get("replay") or {})
    impl = str(replay_cfg.get("impl", "local"))
    if impl == "local":
        buffer = make_item_buffer(
            max_length=buffer_size,
            min_length=batch_size,
            sample_batch_size=batch_size,
            add_batch_size=int(config.system.rollout_length) * local_envs,
        )
    elif impl == "sharded":
        from stoix_tpu.replay.compat import make_sharded_item_buffer

        if bool(replay_cfg.get("prioritized", False)):
            # The 4-function ItemBuffer interface this family consumes has
            # no set_priorities seam, so priorities would freeze at the
            # insert value and sampling would stay exactly uniform —
            # refuse rather than silently no-op the knob. The prioritized
            # path is Sebulba ff_dqn, whose learn program scatters TD
            # priorities in-program.
            raise ValueError(
                "system.replay.prioritized=true is not supported on the "
                "Anakin item-buffer path (no set_priorities seam in the "
                "ItemBuffer interface); use the Sebulba off-policy path "
                "(systems/q_learning/sebulba/ff_dqn.py) for distributed "
                "prioritized replay"
            )
        buffer = make_sharded_item_buffer(
            capacity_per_shard=buffer_size,
            sample_batch_size=batch_size * n_shards,
            num_shards=n_shards,
            min_fill=max(
                batch_size * n_shards,
                int(replay_cfg.get("min_fill", batch_size * n_shards)),
            ),
            axis="data",
        )
    else:
        raise ValueError(
            f"system.replay.impl must be 'local' or 'sharded', got {impl!r}"
        )
    return buffer, buffer.init(dummy_transition(env, discrete_actions))


def get_random_warmup_fn(env: envs.Environment, config: Any, buffer_add: Callable) -> Callable:
    """Uniform-random-action buffer pre-fill; continuous action spaces."""
    action_space = env.action_space()

    def warmup(state: OffPolicyLearnerState) -> OffPolicyLearnerState:
        def _step(carry, _):
            env_state, timestep, key = carry
            key, act_key = jax.random.split(key)
            n_envs = timestep.reward.shape[0]
            keys = jax.random.split(act_key, n_envs)
            action = jax.vmap(action_space.sample)(keys)
            next_env_state, next_timestep = env.step(env_state, action)
            return (next_env_state, next_timestep, key), make_transition(
                timestep, action, next_timestep
            )

        key, warmup_key = jax.random.split(state.key)
        (env_state, timestep, _), traj = jax.lax.scan(
            _step, (state.env_state, state.timestep, warmup_key), None,
            int(config.system.warmup_steps),
        )
        buffer_state = buffer_add(state.buffer_state, tree_merge_leading_dims(traj, 2))
        return state._replace(
            buffer_state=buffer_state, key=key, env_state=env_state, timestep=timestep
        )

    return warmup


def assemble_off_policy_state(
    config: Any,
    mesh: Mesh,
    env: envs.Environment,
    params: Any,
    opt_states: Any,
    buffer_state: Any,
    key: jax.Array,
    env_key: jax.Array,
) -> Tuple[OffPolicyLearnerState, OffPolicyLearnerState]:
    """Returns (placed learner_state, state_specs)."""
    n_shards = int(mesh.shape["data"])
    update_batch = int(config.arch.get("update_batch_size", 1))

    state_specs = OffPolicyLearnerState(
        params=P(),
        opt_states=P(),
        buffer_state=P("data"),
        key=P("data"),
        env_state=P(None, "data"),
        timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OffPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        buffer_state=jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_shards, update_batch) + x.shape), buffer_state
        ),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    return anakin.place_learner_state(learner_state, mesh, state_specs), state_specs


def trajectory_buffer_sizing(
    config: Any, mesh: Mesh, min_length_time_axis: int
) -> Tuple[int, int, int]:
    """Per-shard trajectory-buffer sizes from the GLOBAL config totals.

    Returns (local_envs, sample_batch_size, max_length_time_axis): the
    global env/batch/buffer totals divided over data shards × update batch
    (reference ff_dqn.py:325-338 divides per device the same way). Shared by
    every sequence-replay system (AWR/MPO/Rainbow/R2D2/MuZero).
    """
    n_shards = int(mesh.shape["data"])
    update_batch = int(config.arch.get("update_batch_size", 1))
    denom = n_shards * update_batch
    local_envs = int(config.arch.total_num_envs) // denom
    if local_envs == 0:
        raise ValueError(
            f"arch.total_num_envs ({config.arch.total_num_envs}) must be >= "
            f"num_data_shards * update_batch_size ({denom})"
        )
    sample_batch = max(1, int(config.system.total_batch_size) // denom)
    max_length = max(
        int(config.system.total_buffer_size) // (denom * local_envs),
        int(min_length_time_axis),
    )
    return local_envs, sample_batch, max_length


def require_first_add_samplable(config: Any) -> None:
    """Guard for warmup-less sequence-replay learners (AZ/sampled-AZ/MZ
    variants): the trajectory buffer silently returns ZERO-initialized
    sequences when no full sequence has been written yet (buffers.py clamps
    n_periods to >= 1), so the first rollout add must already contain at
    least one sampleable start — otherwise every epoch of the first update
    trains on all-zero garbage with no error."""
    seq = int(config.system.get("sample_sequence_length", 8))
    rollout = int(config.system.rollout_length)
    if rollout - seq + 1 <= 0:
        raise ValueError(
            f"system.sample_sequence_length ({seq}) must be <= "
            f"system.rollout_length ({rollout}) for warmup-less replay "
            "learners: the first buffer add must already contain a full "
            "sequence, or early updates silently train on zero-filled samples"
        )


def wrap_learn(
    learn_per_shard: Callable,
    mesh: Mesh,
    state_specs: Any,
) -> Callable:
    """shard_map a learner fn, squeezing the buffer's [S] shard axis per
    shard (every buffer-holding system shares this wrapper)."""

    def per_shard_learn(state):
        squeezed = state._replace(
            buffer_state=jax.tree.map(lambda x: x[0], state.buffer_state)
        )
        out = learn_per_shard(squeezed)
        new_state = out.learner_state._replace(
            buffer_state=jax.tree.map(lambda x: x[None], out.learner_state.buffer_state)
        )
        return out._replace(learner_state=new_state)

    return anakin.shardmap_learner(per_shard_learn, mesh, state_specs)


def wrap_learn_and_warmup(
    learn_per_shard: Callable,
    warmup_core: Callable,
    mesh: Mesh,
    state_specs: Any,
) -> Tuple[Callable, Callable]:
    """shard_map both fns, squeezing the buffer's [S] shard axis per shard."""
    learn = wrap_learn(learn_per_shard, mesh, state_specs)

    def per_shard_warmup(state):
        squeezed = state._replace(
            buffer_state=jax.tree.map(lambda x: x[0], state.buffer_state),
            key=state.key[0],
        )
        out = jax.vmap(warmup_core, axis_name="batch")(squeezed)
        return out._replace(
            buffer_state=jax.tree.map(lambda x: x[None], out.buffer_state),
            key=out.key[None],
        )

    warmup = jax.jit(
        shard_map(
            per_shard_warmup, mesh=mesh, in_specs=(state_specs,),
            # Same Anakin opt-out as systems/anakin.py: the in-shard
            # update-batch vmap axis' pmean fails check_vma's internal
            # assert (JAX limitation, not a spec bug).
            out_specs=state_specs, check_vma=False,
        )
    )
    return learn, warmup


def standard_off_policy_learner(
    env: envs.Environment,
    buffer: Any,
    config: Any,
    update_from_batch: Callable[[Any, Any, Any, jax.Array], Tuple[Tuple[Any, Any], dict]],
    act_in_env: Callable[[Any, Any, jax.Array], jax.Array],
) -> Callable:
    """Standard off-policy learner loop.

    update_from_batch(params, opt_states, batch, key) -> ((params, opt_states), metrics)
    act_in_env(params, observation, key, buffer_state) -> action — buffer_state
    enables training-progress schedules (e.g. epsilon decay keyed on
    buffer_state.num_added); implementations that don't need it take it as an
    unused parameter.
    """

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, act_key = jax.random.split(key)
        action = act_in_env(
            params, last_timestep.observation, act_key, buffer_state=buffer_state
        )
        env_state, timestep = env.step(env_state, action)
        transition = make_transition(last_timestep, action, timestep)
        return (
            OffPolicyLearnerState(params, opt_states, buffer_state, key, env_state, timestep),
            transition,
        )

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key, update_key = jax.random.split(key, 3)
        batch = buffer.sample(buffer_state, sample_key).experience
        (params, opt_states), metrics = update_from_batch(params, opt_states, batch, update_key)
        return (params, opt_states, buffer_state, key), metrics

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        buffer_state = buffer.add(buffer_state, tree_merge_leading_dims(traj, 2))
        (params, opt_states, buffer_state, key), metrics = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj.info, metrics)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def pmean_grads(grads: Any) -> Any:
    grads = jax.lax.pmean(grads, axis_name="batch")
    return jax.lax.pmean(grads, axis_name="data")
