"""Anakin SPO — Sequential Monte Carlo Policy Optimization
(reference stoix/systems/spo/ff_spo.py, 1868 LoC / ff_spo_continuous.py, 1958
LoC — the reference's largest systems).

Core machinery preserved (reference `SPO` class, ff_spo.py:342-983):
  - a population of PARTICLES rolls the real environment forward from the
    current state under the policy (`Particles` :342, `search` :396)
  - particles are weighted by temperature-scaled advantages and RESAMPLED
    (multinomial) whenever the effective sample size drops below a threshold
    (`resample` :797, `calculate_ess_and_entropy` :950)
  - the SMC-improved distribution over FIRST actions is the policy target,
    optimized MPO-style with the FULL dual set (reference spo_types.py:20-29):
    a temperature dual for the E-step AND a KL(target‖online) alpha dual for
    the M-step trust region (reference ff_spo.py:1243-1281), with polyak
    target actor/critic networks (:1408-1414)
  - training is OFF-POLICY from a trajectory buffer of stored search results
    (reference ff_spo.py:1631-1639): sequences are sampled each epoch and the
    critic trains on truncation-aware GAE computed with the TARGET critic
    over the stored sequence (:1310-1318).

Serves discrete and continuous heads from the network config
(ff_spo_continuous shares this learner, as the reference's twin file);
continuous KL constraints use the decomposed per-dimension mean/stddev alphas
shared with MPO (systems/mpo/ff_vmpo.py helpers).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OffPolicyLearnerState, OnlineAndTarget
from stoix_tpu.buffers import make_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import distributions as dists
from stoix_tpu.ops import truncated_generalized_advantage_estimation
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.mpo.ff_vmpo import (
    decoupled_alpha_losses,
    gaussian_kls_per_dim,
    gaussian_params,
    init_log_duals,
    project_duals,
)
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.systems.search.ff_az import unwrap_env_state
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class SPOParams(NamedTuple):
    actor_params: OnlineAndTarget
    critic_params: OnlineAndTarget
    log_temperature: jax.Array  # eta dual for the SMC weights (E-step)
    log_alpha: jax.Array  # KL trust-region dual (M-step); [2, A] continuous


class SPOOptStates(NamedTuple):
    actor_opt_state: Any
    critic_opt_state: Any
    dual_opt_state: Any


class Particles(NamedTuple):
    """SMC particle population for ONE environment (vmapped over envs)."""

    state: Any  # sim env state, leaves [N, ...]
    obs: Any  # Observation, leaves [N, ...]
    first_action: jax.Array  # [N, ...] action taken at the root
    log_weight: jax.Array  # [N] temperature-scaled (resampling behavior)
    raw_adv: jax.Array  # [N] UNscaled advantage sum (for the temperature dual)
    alive: jax.Array  # [N] discount-alive mask


def _softplus(x):
    return jax.nn.softplus(x) + 1e-8


def get_learner_fn(env, sim_env, apply_fns, update_fns, buffer, config, continuous: bool):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update, dual_update = update_fns
    gamma = float(config.system.gamma)
    tau = float(config.system.get("tau", 0.005))
    num_particles = int(config.system.get("num_particles", 16))
    horizon = int(config.system.get("search_horizon", 4))
    ess_threshold = float(config.system.get("ess_threshold", 0.5))
    eps_eta = float(config.system.get("epsilon_eta", 0.1))
    eps_alpha = float(config.system.get("epsilon_policy", 1e-3))
    eps_alpha_mean = float(config.system.get("epsilon_alpha_mean", 0.0075))
    eps_alpha_stddev = float(config.system.get("epsilon_alpha_stddev", 1e-5))

    def _smc_search(params: SPOParams, key, root_state, root_obs):
        """SMC over one env's state: returns (first_actions [N,...], weights [N])."""
        eta = _softplus(params.log_temperature)
        tile = lambda x: jnp.broadcast_to(x, (num_particles,) + x.shape)

        key, act_key = jax.random.split(key)
        root_dist = actor_apply(params.actor_params.online, jax.tree.map(tile, root_obs))
        first_action = root_dist.sample(seed=act_key)

        def step_particles(carry, _):
            particles, key, action = carry
            key, next_act_key, resample_key = jax.random.split(key, 3)

            new_state, ts = jax.vmap(sim_env.step)(particles.state, action)
            v_next = critic_apply(params.critic_params.online, ts.observation)
            v_cur = critic_apply(params.critic_params.online, particles.obs)
            # Advantage-shaped incremental weight, masked once a particle's
            # episode has terminated.
            delta = ts.reward + gamma * ts.discount * v_next - v_cur
            log_weight = particles.log_weight + particles.alive * delta / eta
            raw_adv = particles.raw_adv + particles.alive * delta
            alive = particles.alive * ts.discount

            particles = Particles(
                state=new_state,
                obs=ts.observation,
                first_action=particles.first_action,
                log_weight=log_weight,
                raw_adv=raw_adv,
                alive=alive,
            )

            # ESS-triggered multinomial resampling (reference :797, :950).
            w = jax.nn.softmax(particles.log_weight)
            ess = 1.0 / jnp.sum(w**2)
            do_resample = ess < ess_threshold * num_particles
            idx = jax.random.categorical(
                resample_key, particles.log_weight, shape=(num_particles,)
            )
            resampled = jax.tree.map(lambda x: x[idx], particles)
            resampled = resampled._replace(
                log_weight=jnp.zeros_like(particles.log_weight)
            )
            particles = jax.tree.map(
                lambda a, b: jnp.where(
                    jnp.reshape(do_resample, (1,) * a.ndim), a, b
                )
                if a.ndim > 0
                else jnp.where(do_resample, a, b),
                resampled,
                particles,
            )

            next_dist = actor_apply(params.actor_params.online, particles.obs)
            next_action = next_dist.sample(seed=next_act_key)
            return (particles, key, next_action), ess

        particles = Particles(
            state=jax.tree.map(tile, root_state),
            obs=jax.tree.map(tile, root_obs),
            first_action=first_action,
            log_weight=jnp.zeros((num_particles,)),
            raw_adv=jnp.zeros((num_particles,)),
            alive=jnp.ones((num_particles,)),
        )
        (particles, _, _), _ess_trace = jax.lax.scan(
            step_particles, (particles, key, first_action), None, horizon
        )
        weights = jax.nn.softmax(particles.log_weight)
        return particles.first_action, weights, particles.raw_adv

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, search_key, choice_key = jax.random.split(key, 3)

        root_state = unwrap_env_state(env_state)
        n_envs = last_timestep.reward.shape[0]
        search_keys = jax.random.split(search_key, n_envs)
        p_actions, p_weights, p_advs = jax.vmap(
            lambda k, s, o: _smc_search(params, k, s, o)
        )(
            search_keys,
            root_state,
            last_timestep.observation,
        )

        # Execute one particle's root action, sampled by weight.
        choice = jax.random.categorical(choice_key, jnp.log(p_weights + 1e-9), axis=-1)
        action = jax.vmap(lambda p, c: p[c])(p_actions, choice)
        env_state_new, timestep = env.step(env_state, action)

        data = {
            "done": (timestep.discount == 0.0).astype(jnp.float32),
            "truncated": jnp.logical_and(
                timestep.last(), timestep.discount != 0.0
            ).astype(jnp.float32),
            "action": action,
            "particle_actions": p_actions,
            "particle_weights": p_weights,
            "particle_advs": p_advs,
            "reward": timestep.reward,
            "obs": last_timestep.observation,
            "next_obs": timestep.extras["next_obs"],
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(
                params, opt_states, buffer_state, key, env_state_new, timestep
            ),
            data,
        )

    def _policy_loss_fn(learnable, params: SPOParams, seq):
        """CE to SMC weights + temperature dual + KL(target‖online) alpha dual
        (reference ff_spo.py:1198-1295), over merged [B*L] sequence states."""
        actor_online, log_temperature, log_alpha = learnable
        eta = _softplus(log_temperature)
        obs = jax.tree.map(lambda x: tree_merge_leading_dims(x, 2), seq["obs"])
        p_actions = tree_merge_leading_dims(seq["particle_actions"], 2)  # [BL, N, ...]
        p_weights = tree_merge_leading_dims(seq["particle_weights"], 2)  # [BL, N]
        p_advs = tree_merge_leading_dims(seq["particle_advs"], 2)  # [BL, N]

        online_dist = actor_apply(actor_online, obs)
        target_dist = actor_apply(params.actor_params.target, obs)

        # log pi over each particle's root action: [BL, N].
        log_probs = jax.vmap(online_dist.log_prob, in_axes=1, out_axes=1)(p_actions)
        policy_loss = -jnp.mean(
            jnp.sum(jax.lax.stop_gradient(p_weights) * log_probs, axis=-1)
        )

        # Temperature dual on the RAW advantage sums (MPO form): the logsumexp
        # of advantages/eta carries the spread the dual constrains — applying
        # it to already-normalized weights is identically log(1) and would
        # drive eta to its floor.
        n = p_advs.shape[-1]
        temperature_loss = eta * eps_eta + eta * jnp.mean(
            jax.nn.logsumexp(jax.lax.stop_gradient(p_advs) / eta, axis=-1)
            - jnp.log(jnp.asarray(n, jnp.float32))
        )

        # M-step trust region: KL(target‖online) with a learned alpha dual
        # (reference ff_spo.py:1269-1277; continuous decomposed per-dim as in
        # MPO's continuous_loss).
        if continuous:
            b_loc, b_scale = gaussian_params(target_dist)
            o_loc, o_scale = gaussian_params(online_dist)
            kl_mean, kl_std = gaussian_kls_per_dim(b_loc, b_scale, o_loc, o_scale)
            alpha_loss, kl_loss, kl_metric = decoupled_alpha_losses(
                log_alpha, kl_mean, kl_std, eps_alpha_mean, eps_alpha_stddev
            )
        else:
            kl = jnp.mean(
                dists.Categorical(target_dist.logits).kl_divergence(online_dist)
            )
            alpha = _softplus(log_alpha)
            alpha_loss = jnp.sum(alpha * (eps_alpha - jax.lax.stop_gradient(kl)))
            kl_loss = jnp.sum(jax.lax.stop_gradient(alpha) * kl)
            kl_metric = kl

        entropy = online_dist.entropy().mean()
        total = (
            policy_loss
            + temperature_loss
            + alpha_loss
            + kl_loss
            - float(config.system.get("ent_coef", 0.0)) * entropy
        )
        return total, {
            "policy_loss": policy_loss,
            "temperature": eta,
            "kl": kl_metric,
            "entropy": entropy,
        }

    def _critic_loss_fn(critic_online, params: SPOParams, seq):
        """GAE targets over the stored sequence computed with the TARGET
        critic (reference ff_spo.py:1310-1318), l2 to the online critic."""
        v_tm1 = critic_apply(params.critic_params.target, seq["obs"])  # [B, L]
        v_t = critic_apply(params.critic_params.target, seq["next_obs"])  # [B, L]
        _, targets = truncated_generalized_advantage_estimation(
            jnp.swapaxes(seq["reward"], 0, 1),
            jnp.swapaxes(gamma * (1.0 - seq["done"]), 0, 1),
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=jnp.swapaxes(v_tm1, 0, 1),
            v_t=jnp.swapaxes(v_t, 0, 1),
            truncation_t=jnp.swapaxes(seq["truncated"], 0, 1),
        )
        targets = jnp.swapaxes(targets, 0, 1)  # back to [B, L]
        pred = critic_apply(critic_online, seq["obs"])
        loss = float(config.system.get("vf_coef", 0.5)) * 0.5 * jnp.mean(
            (pred - jax.lax.stop_gradient(targets)) ** 2
        )
        return loss, {"value_loss": loss}

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        seq = buffer.sample(buffer_state, sample_key).experience  # [B, L, ...]

        learnable = (params.actor_params.online, params.log_temperature, params.log_alpha)
        p_grads, p_metrics = jax.grad(_policy_loss_fn, has_aux=True)(
            learnable, params, seq
        )
        critic_grads, c_metrics = jax.grad(_critic_loss_fn, has_aux=True)(
            params.critic_params.online, params, seq
        )
        p_grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((p_grads, critic_grads), axis_name="batch"), axis_name="data"
        )
        actor_grads, temp_grads, alpha_grads = p_grads

        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        actor_online = optax.apply_updates(params.actor_params.online, a_updates)
        actor_target = optax.incremental_update(
            actor_online, params.actor_params.target, tau
        )
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        critic_online = optax.apply_updates(params.critic_params.online, c_updates)
        critic_target = optax.incremental_update(
            critic_online, params.critic_params.target, tau
        )
        d_updates, d_opt = dual_update(
            (temp_grads, alpha_grads), opt_states.dual_opt_state
        )
        log_temperature, log_alpha = optax.apply_updates(
            (params.log_temperature, params.log_alpha), d_updates
        )
        log_temperature, log_alpha = project_duals(log_temperature, log_alpha)

        params = SPOParams(
            OnlineAndTarget(actor_online, actor_target),
            OnlineAndTarget(critic_online, critic_target),
            log_temperature,
            log_alpha,
        )
        return (params, SPOOptStates(a_opt, c_opt, d_opt), buffer_state, key), {
            **p_metrics,
            **c_metrics,
        }

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        batch = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)  # [E, T, ...]
        buffer_state = buffer.add(buffer_state, batch)

        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array):
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    continuous = hasattr(env.action_space(), "low")
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    dual_optim = optax.adam(float(config.system.get("dual_lr", 1e-2)))

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    log_temperature, log_alpha = init_log_duals(config, continuous, int(env.num_actions))
    params = SPOParams(
        OnlineAndTarget(actor_params, actor_params),
        OnlineAndTarget(critic_params, critic_params),
        log_temperature,
        log_alpha,
    )
    opt_states = SPOOptStates(
        actor_optim.init(actor_params),
        critic_optim.init(critic_params),
        dual_optim.init((log_temperature, log_alpha)),
    )

    # Warmup-less replay: the first rollout add must already contain a full
    # sampleable sequence (shared guard with the AZ/MZ family).
    core.require_first_add_samplable(config)

    num_particles = int(config.system.get("num_particles", 16))
    action_value = jnp.asarray(
        env.action_value(), jnp.float32 if continuous else jnp.int32
    )
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=int(config.system.get("sample_sequence_length", 8)),
        period=int(config.system.get("sample_period", 1)),
        max_length_time_axis=max_length,
    )
    dummy_item = {
        "done": jnp.zeros((), jnp.float32),
        "truncated": jnp.zeros((), jnp.float32),
        "action": action_value,
        "particle_actions": jnp.broadcast_to(
            action_value, (num_particles,) + action_value.shape
        ),
        "particle_weights": jnp.zeros((num_particles,), jnp.float32),
        "particle_advs": jnp.zeros((num_particles,), jnp.float32),
        "reward": jnp.zeros((), jnp.float32),
        "obs": env.observation_value(),
        "next_obs": env.observation_value(),
    }
    buffer_state = buffer.init(dummy_item)

    sim_env = envs.make_single(
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario,
        **dict(config.env.get("kwargs", {}) or {}),
    )
    learn_per_shard = get_learner_fn(
        env, sim_env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update, dual_optim.update),
        buffer, config, continuous,
    )
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )
    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params.online),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_spo.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
