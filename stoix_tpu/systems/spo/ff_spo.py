"""Anakin SPO — Sequential Monte Carlo Policy Optimization
(reference stoix/systems/spo/ff_spo.py, 1868 LoC / ff_spo_continuous.py, 1958
LoC — the reference's largest systems).

Core machinery preserved (reference `SPO` class, ff_spo.py:342-983):
  - a population of PARTICLES rolls the real environment forward from the
    current state under the policy (`Particles` :342, `search` :396)
  - particles are weighted by temperature-scaled advantages and RESAMPLED
    (multinomial) whenever the effective sample size drops below a threshold
    (`resample` :797, `calculate_ess_and_entropy` :950)
  - the SMC-improved distribution over FIRST actions is the policy target,
    optimized MPO-style with a learnable temperature dual
    (`spo_types.py:20-29`); the critic trains on truncation-aware GAE.

Serves discrete and continuous heads from the network config
(ff_spo_continuous shares this learner, as the reference's twin file).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OnPolicyLearnerState
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops.multistep import truncated_generalized_advantage_estimation
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.systems.search.ff_az import unwrap_env_state
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class SPOParams(NamedTuple):
    actor_params: Any
    critic_params: Any
    log_temperature: jax.Array  # eta dual for the SMC weights


class SPOOptStates(NamedTuple):
    actor_opt_state: Any
    critic_opt_state: Any
    dual_opt_state: Any


class Particles(NamedTuple):
    """SMC particle population for ONE environment (vmapped over envs)."""

    state: Any  # sim env state, leaves [N, ...]
    obs: Any  # Observation, leaves [N, ...]
    first_action: jax.Array  # [N, ...] action taken at the root
    log_weight: jax.Array  # [N] temperature-scaled (resampling behavior)
    raw_adv: jax.Array  # [N] UNscaled advantage sum (for the temperature dual)
    alive: jax.Array  # [N] discount-alive mask


class SPOTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    particle_actions: jax.Array  # [N, ...] root actions of the particles
    particle_weights: jax.Array  # [N]
    particle_advs: jax.Array  # [N] raw advantage sums (dual loss input)
    value: jax.Array
    reward: jax.Array
    obs: Any
    next_obs: Any
    info: Dict[str, Any]


def _softplus(x):
    return jax.nn.softplus(x) + 1e-8


def get_learner_fn(env, sim_env, apply_fns, update_fns, config):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update, dual_update = update_fns
    gamma = float(config.system.gamma)
    num_particles = int(config.system.get("num_particles", 16))
    horizon = int(config.system.get("search_horizon", 4))
    ess_threshold = float(config.system.get("ess_threshold", 0.5))
    eps_eta = float(config.system.get("epsilon_eta", 0.1))

    def _smc_search(params: SPOParams, key, root_state, root_obs):
        """SMC over one env's state: returns (first_actions [N,...], weights [N])."""
        eta = _softplus(params.log_temperature)
        tile = lambda x: jnp.broadcast_to(x, (num_particles,) + x.shape)

        key, act_key = jax.random.split(key)
        root_dist = actor_apply(params.actor_params, jax.tree.map(tile, root_obs))
        first_action = root_dist.sample(seed=act_key)

        v_root = critic_apply(params.critic_params, root_obs)

        def step_particles(carry, _):
            particles, key, action = carry
            key, next_act_key, resample_key = jax.random.split(key, 3)

            new_state, ts = jax.vmap(sim_env.step)(particles.state, action)
            v_next = critic_apply(params.critic_params, ts.observation)
            v_cur = critic_apply(params.critic_params, particles.obs)
            # Advantage-shaped incremental weight, masked once a particle's
            # episode has terminated.
            delta = ts.reward + gamma * ts.discount * v_next - v_cur
            log_weight = particles.log_weight + particles.alive * delta / eta
            raw_adv = particles.raw_adv + particles.alive * delta
            alive = particles.alive * ts.discount

            particles = Particles(
                state=new_state,
                obs=ts.observation,
                first_action=particles.first_action,
                log_weight=log_weight,
                raw_adv=raw_adv,
                alive=alive,
            )

            # ESS-triggered multinomial resampling (reference :797, :950).
            w = jax.nn.softmax(particles.log_weight)
            ess = 1.0 / jnp.sum(w**2)
            do_resample = ess < ess_threshold * num_particles
            idx = jax.random.categorical(
                resample_key, particles.log_weight, shape=(num_particles,)
            )
            resampled = jax.tree.map(lambda x: x[idx], particles)
            resampled = resampled._replace(
                log_weight=jnp.zeros_like(particles.log_weight)
            )
            particles = jax.tree.map(
                lambda a, b: jnp.where(
                    jnp.reshape(do_resample, (1,) * a.ndim), a, b
                )
                if a.ndim > 0
                else jnp.where(do_resample, a, b),
                resampled,
                particles,
            )

            next_dist = actor_apply(params.actor_params, particles.obs)
            next_action = next_dist.sample(seed=next_act_key)
            return (particles, key, next_action), ess

        particles = Particles(
            state=jax.tree.map(tile, root_state),
            obs=jax.tree.map(tile, root_obs),
            first_action=first_action,
            log_weight=jnp.zeros((num_particles,)),
            raw_adv=jnp.zeros((num_particles,)),
            alive=jnp.ones((num_particles,)),
        )
        (particles, _, _), ess_trace = jax.lax.scan(
            step_particles, (particles, key, first_action), None, horizon
        )
        weights = jax.nn.softmax(particles.log_weight)
        return particles.first_action, weights, particles.raw_adv, jnp.mean(ess_trace), v_root

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, search_key, choice_key = jax.random.split(key, 3)

        root_state = unwrap_env_state(env_state)
        n_envs = last_timestep.reward.shape[0]
        search_keys = jax.random.split(search_key, n_envs)
        p_actions, p_weights, p_advs, ess, value = jax.vmap(
            lambda k, s, o: _smc_search(params, k, s, o)
        )(
            search_keys,
            root_state,
            last_timestep.observation,
        )

        # Execute one particle's root action, sampled by weight.
        choice = jax.random.categorical(choice_key, jnp.log(p_weights + 1e-9), axis=-1)
        action = jax.vmap(lambda p, c: p[c])(p_actions, choice)
        env_state_new, timestep = env.step(env_state, action)

        transition = SPOTransition(
            done=timestep.discount == 0.0,
            truncated=jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            action=action,
            particle_actions=p_actions,
            particle_weights=p_weights,
            particle_advs=p_advs,
            value=value,
            reward=timestep.reward,
            obs=last_timestep.observation,
            next_obs=timestep.extras["next_obs"],
            info=timestep.extras["episode_metrics"],
        )
        return (
            OnPolicyLearnerState(params, opt_states, key, env_state_new, timestep),
            transition,
        )

    def _policy_loss_fn(learnable, obs, p_actions, p_weights, p_advs):
        actor_params, log_temperature = learnable
        eta = _softplus(log_temperature)
        dist = actor_apply(actor_params, obs)
        # log pi over each particle's root action: [B, N].
        log_probs = jax.vmap(dist.log_prob, in_axes=1, out_axes=1)(p_actions)
        policy_loss = -jnp.mean(
            jnp.sum(jax.lax.stop_gradient(p_weights) * log_probs, axis=-1)
        )
        # Temperature dual on the RAW advantage sums (MPO form): the logsumexp
        # of advantages/eta carries the spread the dual constrains — applying
        # it to already-normalized weights is identically log(1) and would
        # drive eta to its floor.
        n = p_advs.shape[-1]
        temperature_loss = eta * eps_eta + eta * jnp.mean(
            jax.nn.logsumexp(jax.lax.stop_gradient(p_advs) / eta, axis=-1)
            - jnp.log(jnp.asarray(n, jnp.float32))
        )
        entropy = dist.entropy().mean()
        total = policy_loss + temperature_loss - float(
            config.system.get("ent_coef", 0.0)
        ) * entropy
        return total, {
            "policy_loss": policy_loss,
            "temperature": eta,
            "entropy": entropy,
        }

    def _critic_loss_fn(critic_params, obs, targets):
        value = critic_apply(critic_params, obs)
        loss = 0.5 * jnp.mean((value - targets) ** 2)
        return loss, {"value_loss": loss}

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        v_t = critic_apply(params.critic_params, traj.next_obs)
        _, targets = truncated_generalized_advantage_estimation(
            traj.reward,
            gamma * (1.0 - traj.done.astype(jnp.float32)),
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=traj.value,
            v_t=v_t,
            truncation_t=traj.truncated.astype(jnp.float32),
        )

        def _epoch(carry, _):
            params, opt_states, key = carry
            flat_obs, flat_pa, flat_pw, flat_padv, flat_tgt = tree_merge_leading_dims(
                (traj.obs, traj.particle_actions, traj.particle_weights,
                 traj.particle_advs, targets), 2
            )
            learnable = (params.actor_params, params.log_temperature)
            grads, p_metrics = jax.grad(_policy_loss_fn, has_aux=True)(
                learnable, flat_obs, flat_pa, flat_pw, flat_padv
            )
            critic_grads, c_metrics = jax.grad(_critic_loss_fn, has_aux=True)(
                params.critic_params, flat_obs, flat_tgt
            )
            grads, critic_grads = jax.lax.pmean(
                jax.lax.pmean((grads, critic_grads), axis_name="batch"), axis_name="data"
            )
            actor_grads, temp_grads = grads
            a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
            c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
            d_updates, d_opt = dual_update(temp_grads, opt_states.dual_opt_state)
            params = SPOParams(
                optax.apply_updates(params.actor_params, a_updates),
                optax.apply_updates(params.critic_params, c_updates),
                optax.apply_updates(params.log_temperature, d_updates),
            )
            return (params, SPOOptStates(a_opt, c_opt, d_opt), key), {
                **p_metrics, **c_metrics,
            }

        (params, opt_states, key), loss_info = jax.lax.scan(
            _epoch, (params, opt_states, key), None, int(config.system.epochs)
        )
        learner_state = OnPolicyLearnerState(params, opt_states, key, env_state, last_timestep)
        return learner_state, (traj.info, loss_info)

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    dual_optim = optax.adam(float(config.system.get("dual_lr", 1e-2)))

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    log_temperature = jnp.asarray(float(config.system.get("init_log_temperature", 1.0)))
    params = SPOParams(actor_params, critic_params, log_temperature)
    opt_states = SPOOptStates(
        actor_optim.init(actor_params),
        critic_optim.init(critic_params),
        dual_optim.init(log_temperature),
    )

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    sim_env = envs.make_single(
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario,
        **dict(config.env.get("kwargs", {}) or {}),
    )
    learn_per_shard = get_learner_fn(
        env, sim_env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update, dual_optim.update), config,
    )
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_spo.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
