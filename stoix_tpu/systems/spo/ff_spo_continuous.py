"""Anakin SPO, continuous actions (reference
stoix/systems/spo/ff_spo_continuous.py, 1958 LoC) — shares the ff_spo SMC
learner; the continuous head comes from the network config."""

from __future__ import annotations

from typing import Any

from stoix_tpu.systems.runner import run_anakin_experiment
from stoix_tpu.systems.spo.ff_spo import learner_setup  # noqa: F401
from stoix_tpu.utils import config as config_lib


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_spo_continuous.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
