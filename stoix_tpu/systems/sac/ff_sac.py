"""Anakin SAC (reference stoix/systems/sac/ff_sac.py, 691 LoC).

Distinctives preserved: learnable `log_alpha` temperature with target entropy
(reference ff_sac.py:154-171), twin-Q minimum backup (:186), squashed-Gaussian
actor, polyak critic targets. Anakin scaffolding shared via off_policy_core.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import OnlineAndTarget, Transition
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


class SACParams(NamedTuple):
    actor_params: Any
    q_params: OnlineAndTarget
    log_alpha: jax.Array


class SACOptStates(NamedTuple):
    actor_opt_state: Any
    q_opt_state: Any
    alpha_opt_state: Any


def _build_networks(env: envs.Environment, config: Any):
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic, MultiNetwork

    action_space = env.action_space()
    action_dim = env.num_actions
    lo = float(jnp.min(jnp.asarray(action_space.low)))
    hi = float(jnp.max(jnp.asarray(action_space.high)))

    net_cfg = config.network
    actor = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head, action_dim=action_dim, minimum=lo, maximum=hi
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    q_network = MultiNetwork(
        [
            FeedForwardCritic(
                critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
                torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
                input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
            )
            for _ in range(2)
        ]
    )
    return actor, q_network, action_dim


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array):
    actor, q_network, action_dim = _build_networks(env, config)
    config.system.action_dim = action_dim
    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    target_entropy = float(config.system.get("target_entropy_scale", 1.0)) * -action_dim
    autotune = bool(config.system.get("autotune_alpha", True))

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.q_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    alpha_optim = optax.adam(float(config.system.get("alpha_lr", 3e-4)))

    key, actor_key, q_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    dummy_act = jnp.asarray(env.action_value(), jnp.float32)[None]
    actor_params = actor.init(actor_key, dummy_obs)
    q_params = q_network.init(q_key, dummy_obs, dummy_act)
    log_alpha = jnp.asarray(float(jnp.log(float(config.system.get("init_alpha", 1.0)))))

    params = SACParams(actor_params, OnlineAndTarget(q_params, q_params), log_alpha)
    opt_states = SACOptStates(
        actor_optim.init(actor_params), q_optim.init(q_params), alpha_optim.init(log_alpha)
    )

    buffer, buffer_state = core.build_buffer(env, config, mesh)

    def q_loss_fn(q_online, obs, action, target):
        q_pred = q_network.apply(q_online, obs, action)  # [B, 2]
        loss = jnp.mean((q_pred - target[:, None]) ** 2)
        return loss, {"q_loss": loss, "mean_q": jnp.mean(q_pred)}

    def actor_loss_fn(actor_params, q_online, log_alpha, obs, key):
        dist = actor.apply(actor_params, obs)
        action, log_prob = dist.sample_and_log_prob(seed=key)
        q = jnp.min(q_network.apply(q_online, obs, action), axis=-1)
        alpha = jnp.exp(log_alpha)
        loss = jnp.mean(alpha * log_prob - q)
        return loss, (log_prob, {"actor_loss": loss, "entropy": -jnp.mean(log_prob)})

    def alpha_loss_fn(log_alpha, log_prob):
        loss = -jnp.mean(log_alpha * jax.lax.stop_gradient(log_prob + target_entropy))
        return loss, {"alpha_loss": loss, "alpha": jnp.exp(log_alpha)}

    def update_from_batch(params: SACParams, opt_states: SACOptStates, batch: Transition, key):
        key, next_key, actor_key = jax.random.split(key, 3)
        # Critic update: twin-target min backup with entropy bonus.
        next_dist = actor.apply(params.actor_params, batch.next_obs)
        next_action, next_log_prob = next_dist.sample_and_log_prob(seed=next_key)
        q_next = jnp.min(
            q_network.apply(params.q_params.target, batch.next_obs, next_action), axis=-1
        )
        alpha = jnp.exp(params.log_alpha)
        d_t = gamma * (1.0 - batch.done.astype(jnp.float32))
        target = jax.lax.stop_gradient(
            batch.reward + d_t * (q_next - alpha * next_log_prob)
        )
        q_grads, q_metrics = jax.grad(q_loss_fn, has_aux=True)(
            params.q_params.online, batch.obs, batch.action, target
        )
        q_grads = core.pmean_grads(q_grads)
        q_updates, q_opt_state = q_optim.update(q_grads, opt_states.q_opt_state)
        q_online = optax.apply_updates(params.q_params.online, q_updates)
        q_target = optax.incremental_update(q_online, params.q_params.target, tau)

        # Actor update.
        actor_grads, (log_prob, actor_metrics) = jax.grad(actor_loss_fn, has_aux=True)(
            params.actor_params, q_online, params.log_alpha, batch.obs, actor_key
        )
        actor_grads = core.pmean_grads(actor_grads)
        actor_updates, actor_opt_state = actor_optim.update(
            actor_grads, opt_states.actor_opt_state
        )
        actor_params = optax.apply_updates(params.actor_params, actor_updates)

        # Temperature update.
        if autotune:
            alpha_grads, alpha_metrics = jax.grad(alpha_loss_fn, has_aux=True)(
                params.log_alpha, log_prob
            )
            alpha_grads = core.pmean_grads(alpha_grads)
            alpha_updates, alpha_opt_state = alpha_optim.update(
                alpha_grads, opt_states.alpha_opt_state
            )
            log_alpha = optax.apply_updates(params.log_alpha, alpha_updates)
        else:
            alpha_metrics = {"alpha_loss": jnp.zeros(()), "alpha": alpha}
            alpha_opt_state = opt_states.alpha_opt_state
            log_alpha = params.log_alpha

        new_params = SACParams(actor_params, OnlineAndTarget(q_online, q_target), log_alpha)
        new_opts = SACOptStates(actor_opt_state, q_opt_state, alpha_opt_state)
        return (new_params, new_opts), {**q_metrics, **actor_metrics, **alpha_metrics}

    def act_in_env(params: SACParams, observation, key, buffer_state=None):
        return actor.apply(params.actor_params, observation).sample(seed=key)

    learn_per_shard = core.standard_off_policy_learner(
        env, buffer, config, update_from_batch, act_in_env
    )
    warmup_core_fn = core.get_random_warmup_fn(env, config, buffer.add)

    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )
    learn, warmup = core.wrap_learn_and_warmup(
        learn_per_shard, warmup_core_fn, mesh, state_specs
    )

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )
    return setup, warmup


def run_experiment(config: Any) -> float:
    holder = {}

    def setup_fn(env, cfg, mesh, key):
        setup, warmup = learner_setup(env, cfg, mesh, key)
        holder["warmup"] = warmup
        return setup

    return run_anakin_experiment(config, setup_fn, warmup_fn=lambda s: holder["warmup"](s))


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_sac.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
