"""Anakin REINFORCE with a critic baseline
(reference stoix/systems/vpg/ff_reinforce.py, 492 LoC — the simplest template).

One policy-gradient update per rollout: n-step discounted return targets
(reference uses n-step returns), advantage = G - V(s), losses
-log pi(a|s) * adv and 0.5 (V - G)^2. Serves discrete and continuous heads
(ff_reinforce_continuous shares this learner, as the reference's twin file).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
    OnPolicyLearnerState,
)
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import truncated_generalized_advantage_estimation
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def get_learner_fn(env, apply_fns, update_fns, config):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, policy_key = jax.random.split(key)
        dist = actor_apply(params.actor_params, last_timestep.observation)
        action = dist.sample(seed=policy_key)
        log_prob = dist.log_prob(action)
        env_state, timestep = env.step(env_state, action)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "log_prob": log_prob,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "truncated": jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            "next_obs": timestep.extras["next_obs"],
            "info": timestep.extras["episode_metrics"],
        }
        return OnPolicyLearnerState(params, opt_states, key, env_state, timestep), data

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        # Returns must not bleed across auto-reset boundaries: use the
        # truncation-aware recursion (GAE with lambda=1 gives
        # G_t = r + gamma*discount*G_{t+1}, resetting to the bootstrap value of
        # the TRUE next obs at truncations). Terminations cut via discount=0.
        v_tm1 = jax.lax.stop_gradient(critic_apply(params.critic_params, traj["obs"]))
        v_t = jax.lax.stop_gradient(critic_apply(params.critic_params, traj["next_obs"]))
        _, g_t = truncated_generalized_advantage_estimation(
            traj["reward"],
            gamma * traj["discount"],
            1.0,
            v_tm1=v_tm1,
            v_t=v_t,
            truncation_t=traj["truncated"].astype(jnp.float32),
        )

        def actor_loss_fn(actor_params):
            dist = actor_apply(actor_params, traj["obs"])
            log_prob = dist.log_prob(traj["action"])
            adv = g_t - v_tm1
            loss = -jnp.mean(log_prob * jax.lax.stop_gradient(adv))
            entropy = dist.entropy().mean()
            total = loss - float(config.system.get("ent_coef", 0.0)) * entropy
            return total, {"actor_loss": loss, "entropy": entropy}

        def critic_loss_fn(critic_params):
            value = critic_apply(critic_params, traj["obs"])
            loss = 0.5 * jnp.mean((value - jax.lax.stop_gradient(g_t)) ** 2)
            return loss, {"value_loss": loss}

        actor_grads, actor_metrics = jax.grad(actor_loss_fn, has_aux=True)(
            params.actor_params
        )
        critic_grads, critic_metrics = jax.grad(critic_loss_fn, has_aux=True)(
            params.critic_params
        )
        for_sync = (actor_grads, critic_grads)
        for_sync = jax.lax.pmean(for_sync, axis_name="batch")
        actor_grads, critic_grads = jax.lax.pmean(for_sync, axis_name="data")

        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        actor_params = optax.apply_updates(params.actor_params, a_updates)
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        critic_params = optax.apply_updates(params.critic_params, c_updates)

        learner_state = OnPolicyLearnerState(
            ActorCriticParams(actor_params, critic_params),
            ActorCriticOptStates(a_opt, c_opt),
            key, env_state, last_timestep,
        )
        return learner_state, (traj["info"], {**actor_metrics, **critic_metrics})

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config), eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(
        env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update), config,
    )
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_reinforce.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
