"""Anakin Sampled MuZero (reference stoix/systems/search/ff_sampled_mz.py,
978 LoC): continuous-action MuZero — K actions sampled from the policy form
the search's action set (as in ff_sampled_az), but the simulator is the
LEARNED RewardBasedWorldModel over latents (as in ff_mz), with per-node
action resampling at every expanded latent.

Training mirrors ff_mz's replay design (reference ff_sampled_mz.py follows
the same buffer/unroll scheme as ff_mz): trajectory buffer; n-step value
targets bootstrapped from stored SEARCH values; unroll-(L-1) training from
the first observation's latent with categorical (two-hot, signed-hyperbolic)
value/reward heads; policy matches search weights over the STORED sampled
action set; sequence breaks on termination/truncation.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OffPolicyLearnerState
from stoix_tpu.buffers import make_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import muzero_pair, n_step_bootstrapped_returns
from stoix_tpu.search import mcts
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import scale_gradient
from stoix_tpu.utils.training import make_learning_rate


class SampledMZParams(NamedTuple):
    world_model: Any
    policy_head: Any
    value_head: Any


class SampledMZOptStates(NamedTuple):
    opt_state: Any


def get_learner_fn(env, networks, optim_update, buffer, config):
    wm, policy_net, value_net = networks
    gamma = float(config.system.gamma)
    num_simulations = int(config.system.get("num_simulations", 25))
    num_samples = int(config.system.get("num_sampled_actions", 8))
    n_steps = int(config.system.get("n_steps", 5))
    ent_coef = float(config.system.get("ent_coef", 0.005))
    vf_coef = float(config.system.get("vf_coef", 0.25))
    root_noise = float(config.system.get("root_exploration_fraction", 0.1))
    space = env.action_space()
    # Per-dimension bounds, broadcast against the trailing action axis.
    act_lo = np.asarray(getattr(space, "low", -1.0), np.float32)
    act_hi = np.asarray(getattr(space, "high", 1.0), np.float32)
    num_atoms = int(config.system.get("num_atoms", 601))
    vmin = float(config.system.get("vmin", -300.0))
    vmax = float(config.system.get("vmax", 300.0))
    # One codec serves both value and reward heads (same support).
    critic_pair = reward_pair = muzero_pair(num_atoms, vmin, vmax)
    search_method = str(config.system.get("search_method", "muzero"))
    policy_fn = (
        mcts.gumbel_muzero_policy if search_method == "gumbel" else mcts.muzero_policy
    )

    def recurrent_fn(params: SampledMZParams, rng, action_idx, embedding):
        latent, actions = embedding["latent"], embedding["actions"]
        action = jnp.take_along_axis(
            actions, action_idx[:, None, None].repeat(actions.shape[-1], -1), axis=1
        )[:, 0]
        new_latent, reward_logits = wm.apply(
            params.world_model, latent, action, method="step"
        )
        reward = reward_pair.apply_inv(reward_logits)
        value = critic_pair.apply_inv(value_net.apply(params.value_head, new_latent))
        # Per-node resampling from the policy at the NEW latent.
        dist = policy_net.apply(params.policy_head, new_latent)
        node_keys = jax.random.split(rng, num_samples)
        node_actions = jnp.swapaxes(
            jax.vmap(lambda k: dist.sample(seed=k))(node_keys), 0, 1
        )  # [B, K, A]
        out = mcts.RecurrentFnOutput(
            reward=reward,
            discount=jnp.full_like(reward, gamma),
            prior_logits=jnp.zeros(reward.shape + (num_samples,)),
            value=value,
        )
        return out, {"latent": new_latent, "actions": node_actions}

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, sample_key, search_key = jax.random.split(key, 3)

        latent = wm.apply(
            params.world_model, last_timestep.observation.agent_view, method="initial_state"
        )
        dist = policy_net.apply(params.policy_head, latent)
        sample_keys = jax.random.split(sample_key, num_samples)
        sampled = jnp.swapaxes(
            jax.vmap(lambda k: dist.sample(seed=k))(sample_keys), 0, 1
        )  # [E, K, A]
        if root_noise > 0.0:
            key, noise_key = jax.random.split(key)
            sampled = mcts.blend_root_action_noise(
                noise_key, sampled, root_noise, act_lo, act_hi
            )
        value = critic_pair.apply_inv(value_net.apply(params.value_head, latent))

        root = mcts.RootFnOutput(
            prior_logits=jnp.zeros(value.shape + (num_samples,)),
            value=value,
            embedding={"latent": latent, "actions": sampled},
        )
        search_out = policy_fn(
            params, search_key, root, recurrent_fn, num_simulations,
            max_depth=int(config.system.get("max_depth") or num_simulations),
        )
        action = jnp.take_along_axis(
            sampled, search_out.action[:, None, None].repeat(sampled.shape[-1], -1), axis=1
        )[:, 0]
        env_state_new, timestep = env.step(env_state, action)

        # Model value of the TRUE successor, for truncated steps: n-step
        # targets must bootstrap through the step-limit boundary (on Pendulum
        # every episode ends by truncation; a zero bootstrap there biases all
        # boundary-window value targets toward 0, i.e. UP for negative-return
        # tasks).
        boot_latent = wm.apply(
            params.world_model,
            timestep.extras["next_obs"].agent_view,
            method="initial_state",
        )
        bootstrap_value = critic_pair.apply_inv(
            value_net.apply(params.value_head, boot_latent)
        )
        data = {
            "obs": last_timestep.observation.agent_view,
            "action": action,
            "sampled_actions": sampled,
            "search_policy": search_out.action_weights,
            "search_value": search_out.search_value,
            "bootstrap_value": bootstrap_value,
            "reward": timestep.reward,
            "done": (timestep.discount == 0.0).astype(jnp.float32),
            "truncated": jnp.logical_and(
                timestep.last(), timestep.discount != 0.0
            ).astype(jnp.float32),
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(
                params, opt_states, buffer_state, key, env_state_new, timestep
            ),
            data,
        )

    def _loss_fn(params: SampledMZParams, seq):
        # seq: [B, L, ...]; train on the first L-1 steps.
        r_t = seq["reward"][:, :-1]
        done = seq["done"].astype(jnp.float32)[:, :-1]
        truncated = seq["truncated"].astype(jnp.float32)[:, :-1]
        # No n-step accumulation across the auto-reset boundary (see
        # ff_mz._loss_fn) — but truncated boundaries still bootstrap: fold
        # gamma * V(true successor) into the boundary reward for the VALUE
        # targets only, then cut the chain (r' + cut = r + gamma*V_boot with
        # no next-episode leakage). The reward model keeps training on the
        # raw environment reward r_t.
        value_r = r_t + gamma * truncated * seq["bootstrap_value"][:, :-1]
        d_t = gamma * (1.0 - done) * (1.0 - truncated)
        value_targets = n_step_bootstrapped_returns(
            value_r, d_t, seq["search_value"][:, 1:], n_steps
        )  # [B, L-1]

        latent = wm.apply(params.world_model, seq["obs"][:, 0], method="initial_state")

        def unroll_step(carry, targets_t):
            latent, mask = carry
            (action, sampled, weights, rew_target, val_target, done_t,
             truncated_t) = targets_t
            dist = policy_net.apply(params.policy_head, latent)
            value_logits = value_net.apply(params.value_head, latent)

            # Policy: weighted max-likelihood over the STORED sampled action
            # set, masked past episode end; entropy bonus keeps the Gaussian
            # from collapsing early (reference ent_coef).
            log_probs = jax.vmap(dist.log_prob, in_axes=1, out_axes=1)(sampled)  # [B, K]
            ce = -jnp.sum(weights * log_probs, axis=-1)
            policy_loss = jnp.mean(ce * mask)
            entropy = jnp.mean(dist.entropy() * mask)

            val_probs = critic_pair.apply(val_target * mask)
            value_loss = vf_coef * jnp.mean(
                optax.softmax_cross_entropy(value_logits, val_probs)
                * (1.0 - truncated_t * mask)
            )

            latent_scaled = scale_gradient(latent, 0.5)
            new_latent, reward_logits = wm.apply(
                params.world_model, latent_scaled, action, method="step"
            )
            rew_probs = reward_pair.apply(rew_target * mask)
            reward_loss = jnp.mean(optax.softmax_cross_entropy(reward_logits, rew_probs))

            new_mask = mask * (1.0 - done_t) * (1.0 - truncated_t)
            metrics = {
                "policy_loss": policy_loss,
                "value_loss": value_loss,
                "reward_loss": reward_loss,
                "entropy": entropy,
            }
            return (new_latent, new_mask), metrics

        targets = (
            seq["action"][:, :-1],
            seq["sampled_actions"][:, :-1],
            seq["search_policy"][:, :-1],
            r_t,
            value_targets,
            done,
            truncated,
        )
        targets = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), targets)
        init_mask = jnp.ones_like(r_t[:, 0])
        (_, _), metrics = jax.lax.scan(unroll_step, (latent, init_mask), targets)
        metrics = jax.tree.map(jnp.mean, metrics)
        total = (
            metrics["policy_loss"]
            + metrics["value_loss"]
            + metrics["reward_loss"]
            - ent_coef * metrics["entropy"]
        )
        return total, metrics

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        seq = buffer.sample(buffer_state, sample_key).experience
        grads, metrics = jax.grad(_loss_fn, has_aux=True)(params, seq)
        grads = jax.lax.pmean(jax.lax.pmean(grads, axis_name="batch"), axis_name="data")
        updates, opt_state = optim_update(grads, opt_states.opt_state)
        params = optax.apply_updates(params, updates)
        return (params, SampledMZOptStates(opt_state), buffer_state, key), metrics

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        buffer_state = buffer.add(
            buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        )
        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    import flax.linen as nn

    from stoix_tpu.networks import heads as heads_lib, torso as torso_lib
    from stoix_tpu.networks.model_based import RewardBasedWorldModel

    config.system.action_dim = env.num_actions
    action_dim = env.num_actions
    space = env.action_space()
    lo = float(jnp.min(jnp.asarray(space.low)))
    hi = float(jnp.max(jnp.asarray(space.high)))
    hidden = int(config.system.get("wm_hidden_size", 64))
    num_atoms = int(config.system.get("num_atoms", 601))
    num_samples = int(config.system.get("num_sampled_actions", 8))

    from stoix_tpu.networks.heads import MLPLogitsHead

    wm = RewardBasedWorldModel(
        obs_encoder=torso_lib.MLPTorso((hidden,)),
        reward_head=MLPLogitsHead(num_outputs=num_atoms, hidden_sizes=(hidden,)),
        action_embedder=torso_lib.MLPTorso((hidden // 2,)),
        hidden_size=hidden,
        num_rnn_layers=int(config.system.get("wm_rnn_layers", 1)),
        rnn_cell_type=str(config.system.get("wm_cell_type", "lstm")),
    )

    class LatentPolicy(nn.Module):
        @nn.compact
        def __call__(self, latent):
            x = torso_lib.MLPTorso((hidden,))(latent)
            return heads_lib.NormalAffineTanhDistributionHead(
                action_dim=action_dim, minimum=lo, maximum=hi
            )(x)

    policy_net = LatentPolicy()
    value_net = MLPLogitsHead(num_outputs=num_atoms, hidden_sizes=(hidden,))

    key, wm_key, p_key, v_key, env_key = jax.random.split(key, 5)
    dummy_view = env.observation_value().agent_view[None]
    dummy_action = jnp.asarray(env.action_value(), jnp.float32)[None]
    wm_params = wm.init(wm_key, dummy_view, dummy_action)
    dummy_latent = wm.apply(wm_params, dummy_view, method="initial_state")
    params = SampledMZParams(
        world_model=wm_params,
        policy_head=policy_net.init(p_key, dummy_latent),
        value_head=value_net.init(v_key, dummy_latent),
    )
    optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    opt_states = SampledMZOptStates(optim.init(params))

    core.require_first_add_samplable(config)
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=int(config.system.get("sample_sequence_length", 6)),
        period=int(config.system.get("sample_period", 1)),
        max_length_time_axis=max_length,
    )
    dummy_item = {
        "obs": env.observation_value().agent_view,
        "action": jnp.zeros((action_dim,), jnp.float32),
        "sampled_actions": jnp.zeros((num_samples, action_dim), jnp.float32),
        "search_policy": jnp.zeros((num_samples,), jnp.float32),
        "search_value": jnp.zeros((), jnp.float32),
        "bootstrap_value": jnp.zeros((), jnp.float32),
        "reward": jnp.zeros((), jnp.float32),
        "done": jnp.zeros((), jnp.float32),
        "truncated": jnp.zeros((), jnp.float32),
    }
    buffer_state = buffer.init(dummy_item)

    learn_per_shard = get_learner_fn(
        env, (wm, policy_net, value_net), optim.update, buffer, config
    )
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )
    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    def eval_apply(params: SampledMZParams, observation):
        latent = wm.apply(params.world_model, observation.agent_view, method="initial_state")
        return policy_net.apply(params.policy_head, latent)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_sampled_mz.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
