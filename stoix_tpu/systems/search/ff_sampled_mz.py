"""Anakin Sampled MuZero (reference stoix/systems/search/ff_sampled_mz.py,
978 LoC): continuous-action MuZero — K actions sampled from the policy form
the search's action set (as in ff_sampled_az), but the simulator is the
LEARNED RewardBasedWorldModel over latents (as in ff_mz). Policy trains on
search weights over the samples; value on GAE targets; reward head on observed
rewards via unroll-k.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OnPolicyLearnerState
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops.multistep import truncated_generalized_advantage_estimation
from stoix_tpu.search import mcts
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.systems.search.ff_mz import MZOptStates
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import scale_gradient
from stoix_tpu.utils.training import make_learning_rate


class SampledMZParams(NamedTuple):
    world_model: Any
    policy_head: Any
    value_head: Any


class SampledMZTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    sampled_actions: jax.Array  # [K, A]
    value: jax.Array
    reward: jax.Array
    search_policy: jax.Array  # [K]
    obs: Any
    next_obs: Any
    info: Dict[str, Any]


def get_learner_fn(env, networks, optim_update, config):
    wm, policy_net, value_net = networks
    gamma = float(config.system.gamma)
    num_simulations = int(config.system.get("num_simulations", 16))
    num_samples = int(config.system.get("num_sampled_actions", 8))
    unroll_k = int(config.system.get("unroll_steps", 4))

    def recurrent_fn(params: SampledMZParams, rng, action_idx, embedding):
        latent, actions = embedding["latent"], embedding["actions"]
        action = jnp.take_along_axis(
            actions, action_idx[:, None, None].repeat(actions.shape[-1], -1), axis=1
        )[:, 0]
        new_latent, reward = wm.apply(params.world_model, latent, action, method="step")
        value = value_net.apply(params.value_head, new_latent)
        # Per-node resampling from the policy at the NEW latent.
        dist = policy_net.apply(params.policy_head, new_latent)
        node_keys = jax.random.split(rng, num_samples)
        node_actions = jnp.swapaxes(
            jax.vmap(lambda k: dist.sample(seed=k))(node_keys), 0, 1
        )  # [B, K, A]
        out = mcts.RecurrentFnOutput(
            reward=reward,
            discount=jnp.full_like(reward, gamma),
            prior_logits=jnp.zeros(reward.shape + (num_samples,)),
            value=value,
        )
        return out, {"latent": new_latent, "actions": node_actions}

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, sample_key, search_key = jax.random.split(key, 3)

        latent = wm.apply(
            params.world_model, last_timestep.observation.agent_view, method="initial_state"
        )
        dist = policy_net.apply(params.policy_head, latent)
        sample_keys = jax.random.split(sample_key, num_samples)
        sampled = jnp.swapaxes(
            jax.vmap(lambda k: dist.sample(seed=k))(sample_keys), 0, 1
        )  # [E, K, A]
        value = value_net.apply(params.value_head, latent)

        root = mcts.RootFnOutput(
            prior_logits=jnp.zeros(value.shape + (num_samples,)),
            value=value,
            embedding={"latent": latent, "actions": sampled},
        )
        search_out = mcts.muzero_policy(
            params, search_key, root, recurrent_fn, num_simulations,
            max_depth=int(config.system.get("max_depth", num_simulations)),
        )
        action = jnp.take_along_axis(
            sampled, search_out.action[:, None, None].repeat(sampled.shape[-1], -1), axis=1
        )[:, 0]
        env_state_new, timestep = env.step(env_state, action)

        transition = SampledMZTransition(
            done=timestep.discount == 0.0,
            truncated=jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            action=action,
            sampled_actions=sampled,
            value=value,
            reward=timestep.reward,
            search_policy=search_out.action_weights,
            obs=last_timestep.observation,
            next_obs=timestep.extras["next_obs"],
            info=timestep.extras["episode_metrics"],
        )
        return (
            OnPolicyLearnerState(params, opt_states, key, env_state_new, timestep),
            transition,
        )

    def _loss_fn(params: SampledMZParams, traj: SampledMZTransition, targets):
        T = targets.shape[0]
        T_train = T - unroll_k + 1

        def window(x, i):
            return jax.lax.dynamic_slice_in_dim(x, i, T_train, axis=0)

        latent = wm.apply(
            params.world_model,
            jax.tree.map(lambda x: x[:T_train], traj.obs.agent_view),
            method="initial_state",
        )

        def unroll_step(carry, i):
            latent, total = carry
            dist = policy_net.apply(params.policy_head, latent)
            value = value_net.apply(params.value_head, latent)
            sampled = window(traj.sampled_actions, i)  # [T', E, K, A]
            weights = window(traj.search_policy, i)  # [T', E, K]
            log_probs = jax.vmap(dist.log_prob, in_axes=2, out_axes=2)(sampled)
            policy_loss = -jnp.mean(jnp.sum(weights * log_probs, axis=-1))
            value_loss = 0.5 * jnp.mean((value - window(targets, i)) ** 2)

            action = window(traj.action, i)
            new_latent, pred_reward = wm.apply(
                params.world_model, latent, action, method="step"
            )
            reward_loss = 0.5 * jnp.mean((pred_reward - window(traj.reward, i)) ** 2)
            new_latent = scale_gradient(new_latent, 0.5)
            return (new_latent, total + policy_loss + value_loss + reward_loss), {
                "policy_loss": policy_loss,
                "value_loss": value_loss,
                "reward_loss": reward_loss,
            }

        (_, total), metrics = jax.lax.scan(
            unroll_step, (latent, jnp.zeros(())), jnp.arange(unroll_k)
        )
        return total / unroll_k, jax.tree.map(jnp.mean, metrics)

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        latent_next = wm.apply(
            params.world_model, traj.next_obs.agent_view, method="initial_state"
        )
        v_t = value_net.apply(params.value_head, latent_next)
        _, targets = truncated_generalized_advantage_estimation(
            traj.reward,
            gamma * (1.0 - traj.done.astype(jnp.float32)),
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=jax.lax.stop_gradient(traj.value),
            v_t=jax.lax.stop_gradient(v_t),
            truncation_t=traj.truncated.astype(jnp.float32),
        )

        def _epoch(carry, _):
            params, opt_states, key = carry
            grads, metrics = jax.grad(_loss_fn, has_aux=True)(params, traj, targets)
            grads = jax.lax.pmean(jax.lax.pmean(grads, axis_name="batch"), axis_name="data")
            updates, opt_state = optim_update(grads, opt_states.opt_state)
            params = optax.apply_updates(params, updates)
            return (params, MZOptStates(opt_state), key), metrics

        (params, opt_states, key), loss_info = jax.lax.scan(
            _epoch, (params, opt_states, key), None, int(config.system.epochs)
        )
        learner_state = OnPolicyLearnerState(params, opt_states, key, env_state, last_timestep)
        return learner_state, (traj.info, loss_info)

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    import flax.linen as nn

    from stoix_tpu.networks import heads as heads_lib, torso as torso_lib
    from stoix_tpu.networks.model_based import RewardBasedWorldModel

    config.system.action_dim = env.num_actions
    action_dim = env.num_actions
    space = env.action_space()
    lo = float(jnp.min(jnp.asarray(space.low)))
    hi = float(jnp.max(jnp.asarray(space.high)))
    hidden = int(config.system.get("wm_hidden_size", 64))

    wm = RewardBasedWorldModel(
        obs_encoder=torso_lib.MLPTorso((hidden,)),
        reward_head=heads_lib.LinearHead(output_dim=1),
        action_embedder=torso_lib.MLPTorso((hidden // 2,)),
        hidden_size=hidden,
        num_rnn_layers=int(config.system.get("wm_rnn_layers", 1)),
        rnn_cell_type=str(config.system.get("wm_cell_type", "lstm")),
    )

    class LatentPolicy(nn.Module):
        @nn.compact
        def __call__(self, latent):
            x = torso_lib.MLPTorso((hidden,))(latent)
            return heads_lib.NormalAffineTanhDistributionHead(
                action_dim=action_dim, minimum=lo, maximum=hi
            )(x)

    class LatentValue(nn.Module):
        @nn.compact
        def __call__(self, latent):
            x = torso_lib.MLPTorso((hidden,))(latent)
            return heads_lib.ScalarCriticHead()(x)

    policy_net, value_net = LatentPolicy(), LatentValue()

    key, wm_key, p_key, v_key, env_key = jax.random.split(key, 5)
    dummy_view = env.observation_value().agent_view[None]
    dummy_action = jnp.asarray(env.action_value(), jnp.float32)[None]
    wm_params = wm.init(wm_key, dummy_view, dummy_action)
    dummy_latent = wm.apply(wm_params, dummy_view, method="initial_state")
    params = SampledMZParams(
        world_model=wm_params,
        policy_head=policy_net.init(p_key, dummy_latent),
        value_head=value_net.init(v_key, dummy_latent),
    )
    optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    opt_states = MZOptStates(optim.init(params))

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(env, (wm, policy_net, value_net), optim.update, config)
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    def eval_apply(params: SampledMZParams, observation):
        latent = wm.apply(params.world_model, observation.agent_view, method="initial_state")
        return policy_net.apply(params.policy_head, latent)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_sampled_mz.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
