"""Anakin Sampled AlphaZero (reference stoix/systems/search/ff_sampled_az.py,
866 LoC): continuous actions via a SAMPLED action set (Hubert et al. 2021) —
K actions drawn from the current policy form the discrete action set the
search operates over (reference SampledExItTransition.sampled_actions,
search_types.py:31-39); the policy trains toward the search weights over those
samples with -sum_i w_i log pi(a_i | s).

Each expanded node draws a FRESH action set from the policy at its own state
(per-node resampling, as in the paper); tree arrays stay static because the
set size K is fixed.

Training is REPLAY-based, matching the reference: rollouts feed a trajectory
buffer (total_buffer_size/total_batch_size/sample_sequence_length, reference
ff_sampled_az.yaml:15-18); each epoch samples sequences and computes
truncation-aware GAE over the STORED search root values (reference
ff_sampled_az.py:401-405 uses sequence.search_value, not the live critic) —
the same stored-search-value bootstrapping that fixed ff_mz in round 2.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
    OffPolicyLearnerState,
)
from stoix_tpu.buffers import make_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import truncated_generalized_advantage_estimation
from stoix_tpu.search import mcts
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.systems.search.ff_az import unwrap_env_state
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def get_learner_fn(env, sim_env, apply_fns, update_fns, buffer, config):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    num_simulations = int(config.system.get("num_simulations", 16))
    num_samples = int(config.system.get("num_sampled_actions", 8))
    ent_coef = float(config.system.get("ent_coef", 0.005))
    root_noise = float(config.system.get("root_exploration_fraction", 0.1))
    space = env.action_space()
    # Per-dimension bounds, broadcast against the trailing action axis.
    act_lo = np.asarray(getattr(space, "low", -1.0), np.float32)
    act_hi = np.asarray(getattr(space, "high", 1.0), np.float32)
    search_method = str(config.system.get("search_method", "muzero"))
    policy_fn = (
        mcts.gumbel_muzero_policy if search_method == "gumbel" else mcts.muzero_policy
    )

    def recurrent_fn(params, rng, action_idx, embedding):
        # embedding per element: {"state": env state, "actions": [K, A]}.
        state = jax.tree.map(lambda x: x[0], embedding["state"])
        actions = embedding["actions"][0]  # [K, A]
        action = actions[action_idx[0]]
        new_state, ts = sim_env.step(state, action)
        value = critic_apply(params.critic_params, ts.observation)
        # Per-node RESAMPLING (Sampled MuZero): the expanded node's action set
        # is drawn fresh from the policy AT THAT STATE.
        dist = actor_apply(params.actor_params, ts.observation)
        node_keys = jax.random.split(rng, num_samples)
        node_actions = jax.vmap(lambda k: dist.sample(seed=k))(node_keys)  # [K, A]
        out = mcts.RecurrentFnOutput(
            reward=ts.reward[None],
            discount=gamma * ts.discount[None],
            prior_logits=jnp.zeros((1, num_samples)),  # uniform over the set
            value=value[None],
        )
        new_embedding = {
            "state": jax.tree.map(lambda x: x[None], new_state),
            "actions": node_actions[None],
        }
        return out, new_embedding

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, sample_key, search_key = jax.random.split(key, 3)

        dist = actor_apply(params.actor_params, last_timestep.observation)
        sample_keys = jax.random.split(sample_key, num_samples)
        sampled = jax.vmap(lambda k: dist.sample(seed=k))(sample_keys)  # [K, E, A]
        sampled = jnp.swapaxes(sampled, 0, 1)  # [E, K, A]
        if root_noise > 0.0:
            # Root exploration (reference root_exploration_fraction): blend
            # the root's sampled action set toward bounded noise so the
            # search sees actions a collapsing policy would never draw.
            key, noise_key = jax.random.split(key)
            sampled = mcts.blend_root_action_noise(
                noise_key, sampled, root_noise, act_lo, act_hi
            )
        value = critic_apply(params.critic_params, last_timestep.observation)

        root = mcts.RootFnOutput(
            prior_logits=jnp.zeros(value.shape + (num_samples,)),
            value=value,
            embedding={"state": unwrap_env_state(env_state), "actions": sampled},
        )
        search_out = policy_fn(
            params, search_key, root, recurrent_fn, num_simulations,
            max_depth=int(config.system.get("max_depth") or num_simulations),
        )
        action = jnp.take_along_axis(
            sampled, search_out.action[:, None, None].repeat(sampled.shape[-1], -1), axis=1
        )[:, 0]
        env_state_new, timestep = env.step(env_state, action)

        data = {
            "obs": last_timestep.observation,
            "sampled_actions": sampled,
            "search_policy": search_out.action_weights,
            # Root search value: the replay GAE bootstraps from these STORED
            # values (reference ff_sampled_az.py:258,401-405).
            "search_value": search_out.search_value,
            # Critic value of the TRUE successor, for truncated steps: the
            # next stored search value belongs to the following episode (on
            # Pendulum EVERY episode ends by truncation, so this is the
            # boundary value at every episode end).
            "bootstrap_value": critic_apply(
                params.critic_params, timestep.extras["next_obs"]
            ),
            "reward": timestep.reward,
            "discount": timestep.discount,
            "truncated": jnp.logical_and(
                timestep.last(), timestep.discount != 0.0
            ).astype(jnp.float32),
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(
                params, opt_states, buffer_state, key, env_state_new, timestep
            ),
            data,
        )

    def _actor_loss_fn(actor_params, obs, sampled_actions, search_policy):
        dist = actor_apply(actor_params, obs)
        # log pi(a_i | s) for each sampled action: [B, K].
        log_probs = jax.vmap(dist.log_prob, in_axes=1, out_axes=1)(sampled_actions)
        ce = -jnp.mean(jnp.sum(search_policy * log_probs, axis=-1))
        # Entropy bonus (reference ent_coef 0.005) keeps the Gaussian from
        # collapsing before the search has found better actions to weight.
        entropy = dist.entropy().mean()
        loss = ce - ent_coef * entropy
        return loss, {"actor_loss": ce, "entropy": entropy}

    def _critic_loss_fn(critic_params, obs, targets):
        value = critic_apply(critic_params, obs)
        loss = 0.5 * jnp.mean((value - targets) ** 2)
        return float(config.system.get("vf_coef", 0.5)) * loss, {"value_loss": loss}

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        seq = buffer.sample(buffer_state, sample_key).experience  # [B, L, ...]

        # Truncation-aware GAE over the STORED search root values — the value
        # sequence the search actually produced, not the current critic
        # (reference ff_sampled_az.py:401-405). At truncations the next stored
        # search value is the FOLLOWING episode's root: bootstrap those steps
        # from the stored true-successor critic value instead.
        truncated = seq["truncated"][:, :-1]
        v_t = jnp.where(
            truncated > 0,
            seq["bootstrap_value"][:, :-1],
            seq["search_value"][:, 1:],
        )
        _, targets = truncated_generalized_advantage_estimation(
            seq["reward"][:, :-1],
            gamma * seq["discount"][:, :-1],
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=seq["search_value"][:, :-1],
            v_t=v_t,
            truncation_t=truncated,
            batch_major=True,
        )
        train_obs = jax.tree.map(lambda x: x[:, :-1], seq["obs"])
        flatten = lambda x: x.reshape((-1,) + x.shape[2:])  # noqa: E731
        obs = jax.tree.map(flatten, train_obs)
        sampled = flatten(seq["sampled_actions"][:, :-1])
        weights = flatten(seq["search_policy"][:, :-1])
        tgt = flatten(targets)

        actor_grads, actor_metrics = jax.grad(_actor_loss_fn, has_aux=True)(
            params.actor_params, obs, sampled, weights
        )
        critic_grads, critic_metrics = jax.grad(_critic_loss_fn, has_aux=True)(
            params.critic_params, obs, tgt
        )
        actor_grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((actor_grads, critic_grads), axis_name="batch"),
            axis_name="data",
        )
        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        params = ActorCriticParams(
            optax.apply_updates(params.actor_params, a_updates),
            optax.apply_updates(params.critic_params, c_updates),
        )
        return (params, ActorCriticOptStates(a_opt, c_opt), buffer_state, key), {
            **actor_metrics, **critic_metrics,
        }

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        buffer_state = buffer.add(
            buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        )
        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )

    # Trajectory replay buffer (reference ff_sampled_az.yaml:15-18).
    num_samples = int(config.system.get("num_sampled_actions", 8))
    action_dim = int(env.action_value().shape[-1])
    core.require_first_add_samplable(config)
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=int(config.system.get("sample_sequence_length", 8)),
        period=int(config.system.get("sample_period", 1)),
        max_length_time_axis=max_length,
    )
    dummy_item = {
        "obs": env.observation_value(),
        "sampled_actions": jnp.zeros((num_samples, action_dim), jnp.float32),
        "search_policy": jnp.zeros((num_samples,), jnp.float32),
        "search_value": jnp.zeros((), jnp.float32),
        "bootstrap_value": jnp.zeros((), jnp.float32),
        "reward": jnp.zeros((), jnp.float32),
        "discount": jnp.zeros((), jnp.float32),
        "truncated": jnp.zeros((), jnp.float32),
    }
    buffer_state = buffer.init(dummy_item)

    sim_env = envs.make_single(
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario,
        **dict(config.env.get("kwargs", {}) or {}),
    )
    learn_per_shard = get_learner_fn(
        env, sim_env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update), buffer, config,
    )
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )
    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_sampled_az.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
