"""Anakin AlphaZero (reference stoix/systems/search/ff_az.py, 732 LoC).

Expert-iteration with the REAL environment as the search simulator: the
recurrent_fn steps a pristine (non-resetting) copy of the env from unwrapped
states (reference make_recurrent_fn:74-102 uses env_state.unwrapped_state),
`mcts.muzero_policy` / `gumbel_muzero_policy` selected by config
(reference :377-379). The actor trains on search visit-weights (CE) and the
critic on truncation-aware GAE targets.

Training draws from a trajectory REPLAY buffer when
`system.use_replay_buffer` is set (the reference's scheme, ff_az.py:497);
otherwise it runs on-policy epochs over the fresh rollout.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import (
    ActorCriticOptStates,
    ActorCriticParams,
    ExperimentOutput,
    OnPolicyLearnerState,
)
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import truncated_generalized_advantage_estimation
from stoix_tpu.search import mcts
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class ExItTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    value: jax.Array
    reward: jax.Array
    search_policy: jax.Array  # [A] visit weights — the policy target
    search_value: jax.Array
    obs: Any
    next_obs: Any
    info: Dict[str, Any]


def unwrap_env_state(state: Any) -> Any:
    """Descend wrapper states' `inner` fields to the core env state."""
    while hasattr(state, "inner"):
        state = state.inner
    return state


def make_search_fn(sim_env, apply_fns, config):
    """The AZ search step shared by the on-policy and replay learners: build
    the root from the live actor/critic, run MCTS through the pristine
    simulator, return (root value, search output)."""
    actor_apply, critic_apply = apply_fns
    gamma = float(config.system.gamma)
    num_simulations = int(config.system.get("num_simulations", 16))
    search_method = str(config.system.get("search_method", "muzero"))
    policy_fn = (
        mcts.gumbel_muzero_policy if search_method == "gumbel" else mcts.muzero_policy
    )

    def recurrent_fn(params, rng, action, embedding):
        # embedding: {"state": core env state} with a leading [B=1] axis.
        state = jax.tree.map(lambda x: x[0], embedding["state"])
        new_state, ts = sim_env.step(state, action[0])
        prior = actor_apply(params.actor_params, ts.observation)
        value = critic_apply(params.critic_params, ts.observation)
        out = mcts.RecurrentFnOutput(
            reward=ts.reward[None],
            discount=gamma * ts.discount[None],
            prior_logits=prior.logits[None],
            value=value[None],
        )
        return out, {"state": jax.tree.map(lambda x: x[None], new_state)}

    def search(params, search_key, env_state, observation):
        prior = actor_apply(params.actor_params, observation)
        value = critic_apply(params.critic_params, observation)
        root = mcts.RootFnOutput(
            prior_logits=prior.logits,
            value=value,
            embedding={"state": unwrap_env_state(env_state)},
        )
        search_out = policy_fn(
            params, search_key, root, recurrent_fn, num_simulations,
            max_depth=int(config.system.get("max_depth", num_simulations)),
        )
        return value, search_out

    return search


def get_learner_fn(env, sim_env, apply_fns, update_fns, config):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    search_fn = make_search_fn(sim_env, apply_fns, config)

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, search_key = jax.random.split(key)

        value, search_out = search_fn(
            params, search_key, env_state, last_timestep.observation
        )
        action = search_out.action
        env_state_new, timestep = env.step(env_state, action)

        transition = ExItTransition(
            done=timestep.discount == 0.0,
            truncated=jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            action=action,
            value=value,
            reward=timestep.reward,
            search_policy=search_out.action_weights,
            search_value=search_out.search_value,
            obs=last_timestep.observation,
            next_obs=timestep.extras["next_obs"],
            info=timestep.extras["episode_metrics"],
        )
        return (
            OnPolicyLearnerState(params, opt_states, key, env_state_new, timestep),
            transition,
        )

    def _actor_loss_fn(actor_params, obs, search_policy):
        dist = actor_apply(actor_params, obs)
        ce = -jnp.sum(search_policy * jax.nn.log_softmax(dist.logits, axis=-1), axis=-1)
        loss = jnp.mean(ce)
        entropy = dist.entropy().mean()
        return loss - float(config.system.get("ent_coef", 0.0)) * entropy, (loss, entropy)

    def _critic_loss_fn(critic_params, obs, targets):
        value = critic_apply(critic_params, obs)
        loss = 0.5 * jnp.mean((value - targets) ** 2)
        return float(config.system.get("vf_coef", 0.5)) * loss, loss

    def _update_minibatch(train_state, batch):
        params, opt_states = train_state
        obs, search_policy, targets = batch
        actor_grads, (actor_loss, entropy) = jax.grad(_actor_loss_fn, has_aux=True)(
            params.actor_params, obs, search_policy
        )
        critic_grads, value_loss = jax.grad(_critic_loss_fn, has_aux=True)(
            params.critic_params, obs, targets
        )
        actor_grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((actor_grads, critic_grads), axis_name="batch"), axis_name="data"
        )
        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        params = ActorCriticParams(
            optax.apply_updates(params.actor_params, a_updates),
            optax.apply_updates(params.critic_params, c_updates),
        )
        loss_info = {"actor_loss": actor_loss, "value_loss": value_loss, "entropy": entropy}
        return (params, ActorCriticOptStates(a_opt, c_opt)), loss_info

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        # GAE over the MCTS root SEARCH values (reference ff_az.py:268-273
        # passes values=sequence.search_value) — the search-improved value
        # sequence, not the raw critic. v_t is the NEXT step's search value;
        # at truncations (and the rollout tail) the true successor was never
        # searched, so bootstrap those from the critic on next_obs.
        v_t_net = critic_apply(params.critic_params, traj.next_obs)
        sv_next = jnp.concatenate([traj.search_value[1:], v_t_net[-1:]], axis=0)
        v_t = jnp.where(traj.truncated.astype(bool), v_t_net, sv_next)
        _, targets = truncated_generalized_advantage_estimation(
            traj.reward,
            gamma * (1.0 - traj.done.astype(jnp.float32)),
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=traj.search_value,
            v_t=v_t,
            truncation_t=traj.truncated.astype(jnp.float32),
        )

        def _update_epoch(carry, _):
            params, opt_states, key = carry
            key, shuffle_key = jax.random.split(key)
            batch_size = targets.shape[0] * targets.shape[1]
            perm = jax.random.permutation(shuffle_key, batch_size)
            flat = tree_merge_leading_dims((traj.obs, traj.search_policy, targets), 2)
            shuffled = jax.tree.map(lambda x: jnp.take(x, perm, axis=0), flat)
            minibatches = jax.tree.map(
                lambda x: x.reshape((int(config.system.num_minibatches), -1) + x.shape[1:]),
                shuffled,
            )
            (params, opt_states), loss_info = jax.lax.scan(
                _update_minibatch, (params, opt_states), minibatches
            )
            return (params, opt_states, key), loss_info

        (params, opt_states, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, key), None, int(config.system.epochs)
        )
        learner_state = OnPolicyLearnerState(params, opt_states, key, env_state, last_timestep)
        return learner_state, (traj.info, loss_info)

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def get_replay_learner_fn(env, sim_env, apply_fns, update_fns, buffer, config):
    """Replay variant (reference ff_az.py:497): rollouts feed a trajectory
    buffer; each epoch samples sequences and recomputes truncation-aware GAE
    targets with the CURRENT critic before the CE/value update."""
    from stoix_tpu.base_types import OffPolicyLearnerState

    actor_apply, critic_apply = apply_fns
    actor_update, critic_update = update_fns
    gamma = float(config.system.gamma)
    search_fn = make_search_fn(sim_env, apply_fns, config)

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, search_key = jax.random.split(key)
        _, search_out = search_fn(
            params, search_key, env_state, last_timestep.observation
        )
        env_state_new, timestep = env.step(env_state, search_out.action)
        data = {
            "obs": last_timestep.observation,
            "search_policy": search_out.action_weights,
            "search_value": search_out.search_value,
            # Critic value of the TRUE successor, recorded at collection time:
            # the replay GAE needs it at truncations, where the stored next
            # search value belongs to the following episode's first state.
            "bootstrap_value": critic_apply(
                params.critic_params, timestep.extras["next_obs"]
            ),
            "reward": timestep.reward,
            "discount": timestep.discount,
            # float32 to match the sampled-AZ/MZ replay buffers (one dtype for
            # the field across the search family).
            "truncated": jnp.logical_and(
                timestep.last(), timestep.discount != 0.0
            ).astype(jnp.float32),
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(params, opt_states, buffer_state, key, env_state_new, timestep),
            data,
        )

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        seq = buffer.sample(buffer_state, sample_key).experience  # [B, L, ...]

        # GAE targets over the STORED search root values (reference
        # ff_az.py:268-273: values=sequence.search_value) — search-improved,
        # and stable under replay because they don't drift with the critic.
        # At truncations sv[:, 1:] is the NEXT episode's first root value, so
        # bootstrap those steps from the stored true-successor critic value.
        sv = seq["search_value"]  # [B, L]
        truncated = seq["truncated"][:, :-1].astype(jnp.float32)
        v_t = jnp.where(
            truncated > 0, seq["bootstrap_value"][:, :-1], sv[:, 1:]
        )
        _, targets = truncated_generalized_advantage_estimation(
            seq["reward"][:, :-1],
            gamma * seq["discount"][:, :-1],
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=sv[:, :-1],
            v_t=v_t,
            truncation_t=truncated,
            batch_major=True,
        )
        train_obs = jax.tree.map(lambda x: x[:, :-1], seq["obs"])

        def actor_loss_fn(actor_params):
            dist = actor_apply(actor_params, train_obs)
            ce = -jnp.sum(
                seq["search_policy"][:, :-1] * jax.nn.log_softmax(dist.logits, axis=-1),
                axis=-1,
            )
            loss = jnp.mean(ce)
            return loss, {"actor_loss": loss, "entropy": dist.entropy().mean()}

        def critic_loss_fn(critic_params):
            v = critic_apply(critic_params, train_obs)
            loss = 0.5 * jnp.mean((v - jax.lax.stop_gradient(targets)) ** 2)
            return float(config.system.get("vf_coef", 0.5)) * loss, {"value_loss": loss}

        a_grads, a_metrics = jax.grad(actor_loss_fn, has_aux=True)(params.actor_params)
        c_grads, c_metrics = jax.grad(critic_loss_fn, has_aux=True)(params.critic_params)
        a_grads, c_grads = jax.lax.pmean(
            jax.lax.pmean((a_grads, c_grads), axis_name="batch"), axis_name="data"
        )
        a_updates, a_opt = actor_update(a_grads, opt_states.actor_opt_state)
        c_updates, c_opt = critic_update(c_grads, opt_states.critic_opt_state)
        params = ActorCriticParams(
            optax.apply_updates(params.actor_params, a_updates),
            optax.apply_updates(params.critic_params, c_updates),
        )
        return (params, ActorCriticOptStates(a_opt, c_opt), buffer_state, key), {
            **a_metrics, **c_metrics,
        }

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        buffer_state = buffer.add(
            buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        )
        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config,
                                      int(config.system.epochs),
                                      int(config.system.num_minibatches)), eps=1e-5),
    )

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    params = ActorCriticParams(actor_params, critic_params)
    opt_states = ActorCriticOptStates(
        actor_optim.init(actor_params), critic_optim.init(critic_params)
    )

    # Pristine simulator env: raw dynamics only (no metrics/auto-reset), so the
    # search never resets mid-rollout (reference ff_az.py:74-102).
    sim_env = envs.make_single(
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario,
        **dict(config.env.get("kwargs", {}) or {}),
    )

    if bool(config.system.get("use_replay_buffer", False)):
        # Replay mode (reference ff_az.py:497): trajectory buffer feeding
        # sequence-sampled CE/GAE updates.
        from stoix_tpu.buffers import make_trajectory_buffer
        from stoix_tpu.systems import off_policy_core as core

        core.require_first_add_samplable(config)
        local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
            config, mesh, 2 * int(config.system.rollout_length)
        )
        buffer = make_trajectory_buffer(
            add_batch_size=local_envs,
            sample_batch_size=sample_batch,
            sample_sequence_length=int(config.system.get("sample_sequence_length", 8)),
            period=int(config.system.get("sample_period", 1)),
            max_length_time_axis=max_length,
        )
        dummy_item = {
            "obs": env.observation_value(),
            "search_policy": jnp.zeros((env.num_actions,), jnp.float32),
            "search_value": jnp.zeros((), jnp.float32),
            "bootstrap_value": jnp.zeros((), jnp.float32),
            "reward": jnp.zeros((), jnp.float32),
            "discount": jnp.zeros((), jnp.float32),
            "truncated": jnp.zeros((), jnp.float32),
        }
        buffer_state = buffer.init(dummy_item)
        learn_per_shard = get_replay_learner_fn(
            env, sim_env, (actor_network.apply, critic_network.apply),
            (actor_optim.update, critic_optim.update), buffer, config,
        )
        learner_state, state_specs = core.assemble_off_policy_state(
            config, mesh, env, params, opt_states, buffer_state, key, env_key
        )
        learn = core.wrap_learn(learn_per_shard, mesh, state_specs)
        return AnakinSetup(
            learn=learn,
            learner_state=learner_state,
            eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
            eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
        )

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(
        env, sim_env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update), config,
    )
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_az.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
