"""Anakin MuZero (reference stoix/systems/search/ff_mz.py, 845 LoC).

Search in a LEARNED model: the RewardBasedWorldModel encodes observations to a
flat latent, the dynamics RNN rolls latents forward under embedded actions
(reference networks/model_based.py), and prediction heads give priors/values on
latents.

Training follows the reference's replay design (ff_mz.py:220-427):
  - rollouts (acting by MCTS in the learned model) feed a trajectory buffer;
  - each epoch samples [B, L] sequences, computes value targets as n-step
    bootstrapped returns FROM THE STORED SEARCH VALUES (reference :276-284),
    then unrolls the dynamics L-1 steps from the first observation's latent:
    policy CE against search visit-weights, categorical (two-hot,
    signed-hyperbolic) cross-entropy for value and reward (reference :537
    rlax.muzero_pair), losses masked past episode end, latent gradients
    scaled 0.5 between steps (reference scale_gradient usage).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OffPolicyLearnerState
from stoix_tpu.buffers import make_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import muzero_pair, n_step_bootstrapped_returns
from stoix_tpu.search import mcts
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import scale_gradient
from stoix_tpu.utils.training import make_learning_rate


class MZParams(NamedTuple):
    world_model: Any
    policy_head: Any
    value_head: Any


class MZOptStates(NamedTuple):
    opt_state: Any


def get_learner_fn(env, networks, optim_update, buffer, config):
    wm, policy_net, value_net = networks
    gamma = float(config.system.gamma)
    num_simulations = int(config.system.get("num_simulations", 25))
    n_steps = int(config.system.get("n_steps", 5))
    ent_coef = float(config.system.get("ent_coef", 0.0))
    vf_coef = float(config.system.get("vf_coef", 0.25))
    num_atoms = int(config.system.get("num_atoms", 601))
    vmin = float(config.system.get("vmin", -300.0))
    vmax = float(config.system.get("vmax", 300.0))
    # One codec serves both value and reward heads (same support).
    critic_pair = reward_pair = muzero_pair(num_atoms, vmin, vmax)
    search_method = str(config.system.get("search_method", "muzero"))
    policy_fn = (
        mcts.gumbel_muzero_policy if search_method == "gumbel" else mcts.muzero_policy
    )

    def _predict(params: MZParams, latent):
        prior = policy_net.apply(params.policy_head, latent)
        value = critic_pair.apply_inv(value_net.apply(params.value_head, latent))
        return prior, value

    def recurrent_fn(params: MZParams, rng, action, latent):
        new_latent, reward_logits = wm.apply(
            params.world_model, latent, action, method="step"
        )
        reward = reward_pair.apply_inv(reward_logits)
        prior, value = _predict(params, new_latent)
        out = mcts.RecurrentFnOutput(
            reward=reward,
            discount=jnp.full_like(reward, gamma),
            prior_logits=prior.logits,
            value=value,
        )
        return out, new_latent

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, search_key = jax.random.split(key)

        latent = wm.apply(
            params.world_model, last_timestep.observation.agent_view, method="initial_state"
        )
        prior, value = _predict(params, latent)
        root = mcts.RootFnOutput(
            prior_logits=prior.logits, value=value, embedding=latent
        )
        search_out = policy_fn(
            params, search_key, root, recurrent_fn, num_simulations,
            max_depth=int(config.system.get("max_depth") or num_simulations),
        )
        action = search_out.action
        env_state_new, timestep = env.step(env_state, action)

        data = {
            "obs": last_timestep.observation.agent_view,
            "action": action,
            "reward": timestep.reward,
            "done": (timestep.discount == 0.0).astype(jnp.float32),
            "truncated": jnp.logical_and(
                timestep.last(), timestep.discount != 0.0
            ).astype(jnp.float32),
            "search_policy": search_out.action_weights,
            "search_value": search_out.search_value,
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(
                params, opt_states, buffer_state, key, env_state_new, timestep
            ),
            data,
        )

    def _loss_fn(params: MZParams, seq):
        # seq: [B, L, ...]; train on the first L-1 steps.
        r_t = seq["reward"][:, :-1]
        done = seq["done"].astype(jnp.float32)[:, :-1]
        truncated = seq["truncated"].astype(jnp.float32)[:, :-1]
        # Truncation (time limit, discount still 1) must not let returns or
        # the dynamics unroll leak across the auto-reset boundary. The
        # stored search_value after a truncation is the POST-reset state's,
        # so: cut the n-step return there (conservative: no bootstrap) and
        # mask the corrupted boundary step out of the value loss below.
        d_t = gamma * (1.0 - done) * (1.0 - truncated)
        value_targets = n_step_bootstrapped_returns(
            r_t, d_t, seq["search_value"][:, 1:], n_steps
        )  # [B, L-1]

        latent = wm.apply(
            params.world_model, seq["obs"][:, 0], method="initial_state"
        )  # [B, D]

        def unroll_step(carry, targets_t):
            latent, mask = carry
            action, rew_target, pol_target, val_target, done, truncated = targets_t
            prior = policy_net.apply(params.policy_head, latent)
            value_logits = value_net.apply(params.value_head, latent)

            # Policy: CE against search visit-weights, masked past episode end.
            ce = -jnp.sum(
                pol_target * jax.nn.log_softmax(prior.logits, axis=-1), axis=-1
            )
            policy_loss = jnp.mean(ce * mask)
            entropy = jnp.mean(prior.entropy() * mask)

            # Value/reward: categorical CE on two-hot transformed targets.
            # Targets are masked (absorbing state => 0) rather than the loss
            # (reference ff_mz.py:322-339), so past-done steps still train
            # toward the absorbing value. Only the in-episode truncation
            # boundary step is excluded from the value loss: its n-step
            # target has no bootstrap (see _loss_fn).
            val_probs = critic_pair.apply(val_target * mask)
            value_loss = vf_coef * jnp.mean(
                optax.softmax_cross_entropy(value_logits, val_probs)
                * (1.0 - truncated * mask)
            )

            latent_scaled = scale_gradient(latent, 0.5)
            new_latent, reward_logits = wm.apply(
                params.world_model, latent_scaled, action, method="step"
            )
            rew_probs = reward_pair.apply(rew_target * mask)
            reward_loss = jnp.mean(
                optax.softmax_cross_entropy(reward_logits, rew_probs)
            )

            # Sequence break on termination OR truncation — the unroll must
            # not straddle an auto-reset.
            new_mask = mask * (1.0 - done) * (1.0 - truncated)
            metrics = {
                "policy_loss": policy_loss,
                "value_loss": value_loss,
                "reward_loss": reward_loss,
                "entropy": entropy,
            }
            return (new_latent, new_mask), metrics

        targets = (
            seq["action"][:, :-1],
            r_t,
            seq["search_policy"][:, :-1],
            value_targets,
            done,
            truncated,
        )
        targets = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), targets)  # [L-1, B, ...]
        init_mask = jnp.ones_like(r_t[:, 0])
        (_, _), metrics = jax.lax.scan(unroll_step, (latent, init_mask), targets)
        metrics = jax.tree.map(jnp.mean, metrics)
        total = (
            metrics["policy_loss"]
            + metrics["value_loss"]
            + metrics["reward_loss"]
            - ent_coef * metrics["entropy"]
        )
        return total, metrics

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        seq = buffer.sample(buffer_state, sample_key).experience  # [B, L, ...]
        grads, metrics = jax.grad(_loss_fn, has_aux=True)(params, seq)
        grads = jax.lax.pmean(jax.lax.pmean(grads, axis_name="batch"), axis_name="data")
        updates, opt_state = optim_update(grads, opt_states.opt_state)
        params = optax.apply_updates(params, updates)
        return (params, MZOptStates(opt_state), buffer_state, key), metrics

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        buffer_state = buffer.add(
            buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        )
        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    import flax.linen as nn

    from stoix_tpu.networks import torso as torso_lib
    from stoix_tpu.networks.model_based import RewardBasedWorldModel

    config.system.action_dim = env.num_actions
    num_actions = env.num_actions
    hidden = int(config.system.get("wm_hidden_size", 64))
    num_atoms = int(config.system.get("num_atoms", 601))

    from stoix_tpu.networks.heads import MLPLogitsHead

    class ActionOneHot(nn.Module):
        num_actions: int

        @nn.compact
        def __call__(self, action):
            return jax.nn.one_hot(action, self.num_actions)

    wm = RewardBasedWorldModel(
        obs_encoder=torso_lib.MLPTorso((hidden,)),
        reward_head=MLPLogitsHead(num_outputs=num_atoms, hidden_sizes=(hidden,)),
        action_embedder=ActionOneHot(num_actions=num_actions),
        hidden_size=hidden,
        num_rnn_layers=int(config.system.get("wm_rnn_layers", 1)),
        rnn_cell_type=str(config.system.get("wm_cell_type", "lstm")),
    )

    class LatentPolicy(nn.Module):
        @nn.compact
        def __call__(self, latent):
            from stoix_tpu.networks import heads as heads_lib

            x = torso_lib.MLPTorso((hidden,))(latent)
            return heads_lib.CategoricalHead(num_actions=num_actions)(x)

    policy_net = LatentPolicy()
    value_net = MLPLogitsHead(num_outputs=num_atoms, hidden_sizes=(hidden,))

    key, wm_key, p_key, v_key, env_key = jax.random.split(key, 5)
    dummy_view = env.observation_value().agent_view[None]
    dummy_action = jnp.zeros((1,), jnp.int32)
    wm_params = wm.init(wm_key, dummy_view, dummy_action)
    dummy_latent = wm.apply(wm_params, dummy_view, method="initial_state")
    params = MZParams(
        world_model=wm_params,
        policy_head=policy_net.init(p_key, dummy_latent),
        value_head=value_net.init(v_key, dummy_latent),
    )
    optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    opt_states = MZOptStates(optim.init(params))

    core.require_first_add_samplable(config)
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=int(config.system.get("sample_sequence_length", 6)),
        period=int(config.system.get("sample_period", 1)),
        max_length_time_axis=max_length,
    )
    dummy_item = {
        "obs": env.observation_value().agent_view,
        "action": jnp.zeros((), jnp.int32),
        "reward": jnp.zeros((), jnp.float32),
        "done": jnp.zeros((), jnp.float32),
        "truncated": jnp.zeros((), jnp.float32),
        "search_policy": jnp.zeros((num_actions,), jnp.float32),
        "search_value": jnp.zeros((), jnp.float32),
    }
    buffer_state = buffer.init(dummy_item)

    learn_per_shard = get_learner_fn(
        env, (wm, policy_net, value_net), optim.update, buffer, config
    )
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )

    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    def eval_apply(params: MZParams, observation):
        latent = wm.apply(params.world_model, observation.agent_view, method="initial_state")
        return policy_net.apply(params.policy_head, latent)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_mz.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
