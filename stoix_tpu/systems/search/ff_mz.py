"""Anakin MuZero (reference stoix/systems/search/ff_mz.py, 845 LoC).

Search in a LEARNED model: the RewardBasedWorldModel encodes observations to a
flat latent, the dynamics RNN rolls latents forward under embedded actions
(reference networks/model_based.py), and prediction heads give priors/values on
latents. Training is unroll-k (reference scale_gradient usage): from each
window, the policy head matches search visit-weights, the value head matches
GAE targets, the reward head matches observed rewards, with latent gradients
scaled 0.5 between steps.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OnPolicyLearnerState
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops.multistep import truncated_generalized_advantage_estimation
from stoix_tpu.search import mcts
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import scale_gradient
from stoix_tpu.utils.training import make_learning_rate


class MZParams(NamedTuple):
    world_model: Any
    policy_head: Any
    value_head: Any


class MZOptStates(NamedTuple):
    opt_state: Any


class MZTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    value: jax.Array
    reward: jax.Array
    search_policy: jax.Array
    obs: Any
    next_obs: Any
    info: Dict[str, Any]


def get_learner_fn(env, networks, optim_update, config):
    wm, policy_net, value_net = networks
    gamma = float(config.system.gamma)
    num_simulations = int(config.system.get("num_simulations", 16))
    unroll_k = int(config.system.get("unroll_steps", 4))

    def _predict(params: MZParams, latent):
        prior = policy_net.apply(params.policy_head, latent)
        value = value_net.apply(params.value_head, latent)
        return prior, value

    def recurrent_fn(params: MZParams, rng, action, latent):
        new_latent, reward = wm.apply(params.world_model, latent, action, method="step")
        prior, value = _predict(params, new_latent)
        out = mcts.RecurrentFnOutput(
            reward=reward,
            discount=jnp.full_like(reward, gamma),
            prior_logits=prior.logits,
            value=value,
        )
        return out, new_latent

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, search_key = jax.random.split(key)

        latent = wm.apply(
            params.world_model, last_timestep.observation.agent_view, method="initial_state"
        )
        prior, value = _predict(params, latent)
        root = mcts.RootFnOutput(
            prior_logits=prior.logits, value=value, embedding=latent
        )
        search_out = mcts.muzero_policy(
            params, search_key, root, recurrent_fn, num_simulations,
            max_depth=int(config.system.get("max_depth", num_simulations)),
        )
        action = search_out.action
        env_state_new, timestep = env.step(env_state, action)

        transition = MZTransition(
            done=timestep.discount == 0.0,
            truncated=jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            action=action,
            value=value,
            reward=timestep.reward,
            search_policy=search_out.action_weights,
            obs=last_timestep.observation,
            next_obs=timestep.extras["next_obs"],
            info=timestep.extras["episode_metrics"],
        )
        return (
            OnPolicyLearnerState(params, opt_states, key, env_state_new, timestep),
            transition,
        )

    def _loss_fn(params: MZParams, traj: MZTransition, targets):
        T = targets.shape[0]
        T_train = T - unroll_k + 1

        # Windows: index i covers steps [i, i + T_train).
        def window(x, i):
            return jax.lax.dynamic_slice_in_dim(x, i, T_train, axis=0)

        latent = wm.apply(
            params.world_model,
            jax.tree.map(lambda x: x[:T_train], traj.obs.agent_view),
            method="initial_state",
        )  # [T_train, E, D]

        def unroll_step(carry, i):
            latent, total_loss = carry
            prior = policy_net.apply(params.policy_head, latent)
            value = value_net.apply(params.value_head, latent)
            pol_target = window(traj.search_policy, i)
            val_target = window(targets, i)
            rew_target = window(traj.reward, i)

            policy_loss = -jnp.mean(
                jnp.sum(pol_target * jax.nn.log_softmax(prior.logits, axis=-1), axis=-1)
            )
            value_loss = 0.5 * jnp.mean((value - val_target) ** 2)

            action = window(traj.action, i)
            new_latent, pred_reward = wm.apply(
                params.world_model, latent, action, method="step"
            )
            reward_loss = 0.5 * jnp.mean((pred_reward - rew_target) ** 2)
            # Scale latent gradients between unroll steps (MuZero trick).
            new_latent = scale_gradient(new_latent, 0.5)
            step_loss = policy_loss + value_loss + reward_loss
            return (new_latent, total_loss + step_loss), {
                "policy_loss": policy_loss,
                "value_loss": value_loss,
                "reward_loss": reward_loss,
            }

        (final_latent, total_loss), metrics = jax.lax.scan(
            unroll_step, (latent, jnp.zeros(())), jnp.arange(unroll_k)
        )
        metrics = jax.tree.map(jnp.mean, metrics)
        return total_loss / unroll_k, metrics

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        latent_next = wm.apply(
            params.world_model, traj.next_obs.agent_view, method="initial_state"
        )
        v_t = value_net.apply(params.value_head, latent_next)
        latent_cur = wm.apply(
            params.world_model, traj.obs.agent_view, method="initial_state"
        )
        v_tm1 = value_net.apply(params.value_head, latent_cur)
        _, targets = truncated_generalized_advantage_estimation(
            traj.reward,
            gamma * (1.0 - traj.done.astype(jnp.float32)),
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=jax.lax.stop_gradient(v_tm1),
            v_t=jax.lax.stop_gradient(v_t),
            truncation_t=traj.truncated.astype(jnp.float32),
        )

        def _epoch(carry, _):
            params, opt_states, key = carry
            grads, metrics = jax.grad(_loss_fn, has_aux=True)(params, traj, targets)
            grads = jax.lax.pmean(jax.lax.pmean(grads, axis_name="batch"), axis_name="data")
            updates, opt_state = optim_update(grads, opt_states.opt_state)
            params = optax.apply_updates(params, updates)
            return (params, MZOptStates(opt_state), key), metrics

        (params, opt_states, key), loss_info = jax.lax.scan(
            _epoch, (params, opt_states, key), None, int(config.system.epochs)
        )
        learner_state = OnPolicyLearnerState(params, opt_states, key, env_state, last_timestep)
        return learner_state, (traj.info, loss_info)

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    import flax.linen as nn

    from stoix_tpu.networks import heads as heads_lib, torso as torso_lib
    from stoix_tpu.networks.model_based import RewardBasedWorldModel

    config.system.action_dim = env.num_actions
    num_actions = env.num_actions
    hidden = int(config.system.get("wm_hidden_size", 64))

    class ActionOneHot(nn.Module):
        num_actions: int

        @nn.compact
        def __call__(self, action):
            return jax.nn.one_hot(action, self.num_actions)

    wm = RewardBasedWorldModel(
        obs_encoder=torso_lib.MLPTorso((hidden,)),
        reward_head=heads_lib.LinearHead(output_dim=1),
        action_embedder=ActionOneHot(num_actions=num_actions),
        hidden_size=hidden,
        num_rnn_layers=int(config.system.get("wm_rnn_layers", 1)),
        rnn_cell_type=str(config.system.get("wm_cell_type", "lstm")),
    )

    class LatentPolicy(nn.Module):
        @nn.compact
        def __call__(self, latent):
            x = torso_lib.MLPTorso((hidden,))(latent)
            return heads_lib.CategoricalHead(num_actions=num_actions)(x)

    class LatentValue(nn.Module):
        @nn.compact
        def __call__(self, latent):
            x = torso_lib.MLPTorso((hidden,))(latent)
            return heads_lib.ScalarCriticHead()(x)

    policy_net, value_net = LatentPolicy(), LatentValue()

    key, wm_key, p_key, v_key, env_key = jax.random.split(key, 5)
    dummy_view = env.observation_value().agent_view[None]
    dummy_action = jnp.zeros((1,), jnp.int32)
    wm_params = wm.init(wm_key, dummy_view, dummy_action)
    dummy_latent = wm.apply(wm_params, dummy_view, method="initial_state")
    params = MZParams(
        world_model=wm_params,
        policy_head=policy_net.init(p_key, dummy_latent),
        value_head=value_net.init(v_key, dummy_latent),
    )
    optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    opt_states = MZOptStates(optim.init(params))

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(env, (wm, policy_net, value_net), optim.update, config)
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    def eval_apply(params: MZParams, observation):
        latent = wm.apply(params.world_model, observation.agent_view, method="initial_state")
        return policy_net.apply(params.policy_head, latent)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_mz.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
