"""Anakin TD3 (reference stoix/systems/ddpg/ff_td3.py, 699 LoC).

Distinctives: twin-Q via MultiNetwork with min backup, target-policy smoothing
noise, and a delayed (every `policy_frequency` updates) ACTOR update; target
polyak updates run every step, like the reference (ff_td3.py:295-301) — see
the note in update_from_batch.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import OnlineAndTarget, Transition
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.ddpg.ff_ddpg import DDPGOptStates, DDPGParams, build_networks
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array):
    actor, q_network, (act_lo, act_hi) = build_networks(env, config, num_critics=2)
    config.system.action_dim = env.num_actions
    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    noise_sigma = float(config.system.get("exploration_sigma", 0.1))
    smoothing_sigma = float(config.system.get("target_policy_noise", 0.2))
    noise_clip = float(config.system.get("target_noise_clip", 0.5))
    policy_frequency = int(config.system.get("policy_frequency", 2))

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.q_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )

    key, actor_key, q_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    dummy_act = jnp.asarray(env.action_value(), jnp.float32)[None]
    actor_p = actor.init(actor_key, dummy_obs)
    q_p = q_network.init(q_key, dummy_obs, dummy_act)
    params = DDPGParams(OnlineAndTarget(actor_p, actor_p), OnlineAndTarget(q_p, q_p))
    opt_states = DDPGOptStates(actor_optim.init(actor_p), q_optim.init(q_p))
    # Thread an update counter through opt_states for delayed policy updates.
    opt_states = (opt_states, jnp.zeros((), jnp.int32))

    buffer, buffer_state = core.build_buffer(env, config, mesh)

    def q_loss_fn(q_online, obs, action, target):
        q_pred = q_network.apply(q_online, obs, action)  # [B, 2]
        loss = jnp.mean((q_pred - target[:, None]) ** 2)
        return loss, {"q_loss": loss, "mean_q": jnp.mean(q_pred)}

    def actor_loss_fn(actor_online, q_online, obs):
        action = actor.apply(actor_online, obs).mode()
        q = q_network.apply(q_online, obs, action)[..., 0]
        loss = -jnp.mean(q)
        return loss, {"actor_loss": loss}

    def update_from_batch(params: DDPGParams, opt_states_and_count, batch: Transition, key):
        opt_states, count = opt_states_and_count
        # Target-policy smoothing: clipped noise on the target action.
        next_action = actor.apply(params.actor_params.target, batch.next_obs).mode()
        noise = jnp.clip(
            jax.random.normal(key, next_action.shape) * smoothing_sigma,
            -noise_clip, noise_clip,
        )
        next_action = jnp.clip(next_action + noise, act_lo, act_hi)
        q_next = jnp.min(
            q_network.apply(params.q_params.target, batch.next_obs, next_action), axis=-1
        )
        d_t = gamma * (1.0 - batch.done.astype(jnp.float32))
        target = jax.lax.stop_gradient(batch.reward + d_t * q_next)

        q_grads, q_metrics = jax.grad(q_loss_fn, has_aux=True)(
            params.q_params.online, batch.obs, batch.action, target
        )
        q_grads = core.pmean_grads(q_grads)
        q_updates, q_opt_state = q_optim.update(q_grads, opt_states.q_opt_state)
        q_online = optax.apply_updates(params.q_params.online, q_updates)

        # Delayed POLICY update only — target polyak updates run every step
        # (reference ff_td3.py:295-301 vs the masked actor optimizer at
        # :396-405). Delaying the targets as well (the earlier behavior)
        # empirically stalls Pendulum completely (-1146 vs -172 with the
        # delay removed; docs/runs_r3.jsonl td3_diag_*).
        do_policy = (count % policy_frequency) == 0
        actor_grads, actor_metrics = jax.grad(actor_loss_fn, has_aux=True)(
            params.actor_params.online, q_online, batch.obs
        )
        actor_grads = core.pmean_grads(actor_grads)
        actor_updates, new_actor_opt = actor_optim.update(
            actor_grads, opt_states.actor_opt_state
        )
        actor_candidate = optax.apply_updates(params.actor_params.online, actor_updates)
        actor_online = jax.tree.map(
            lambda new, old: jnp.where(do_policy, new, old),
            actor_candidate, params.actor_params.online,
        )
        actor_opt_state = jax.tree.map(
            lambda new, old: jnp.where(do_policy, new, old),
            new_actor_opt, opt_states.actor_opt_state,
        )
        actor_target = optax.incremental_update(
            actor_online, params.actor_params.target, tau
        )
        q_target = optax.incremental_update(q_online, params.q_params.target, tau)

        new_params = DDPGParams(
            OnlineAndTarget(actor_online, actor_target), OnlineAndTarget(q_online, q_target)
        )
        new_opts = (DDPGOptStates(actor_opt_state, q_opt_state), count + 1)
        return (new_params, new_opts), {**q_metrics, **actor_metrics}

    def act_in_env(params: DDPGParams, observation, key, buffer_state=None):
        action = actor.apply(params.actor_params.online, observation).mode()
        noise = jax.random.normal(key, action.shape) * noise_sigma * (act_hi - act_lo) / 2
        return jnp.clip(action + noise, act_lo, act_hi)

    learn_per_shard = core.standard_off_policy_learner(
        env, buffer, config, update_from_batch, act_in_env
    )
    warmup_core_fn = core.get_random_warmup_fn(env, config, buffer.add)
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )
    learn, warmup = core.wrap_learn_and_warmup(learn_per_shard, warmup_core_fn, mesh, state_specs)

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params.online),
    )
    return setup, warmup


def run_experiment(config: Any) -> float:
    holder = {}

    def setup_fn(env, cfg, mesh, key):
        setup, warmup = learner_setup(env, cfg, mesh, key)
        holder["warmup"] = warmup
        return setup

    return run_anakin_experiment(config, setup_fn, warmup_fn=lambda s: holder["warmup"](s))


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_td3.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
