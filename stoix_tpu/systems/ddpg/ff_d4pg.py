"""Anakin D4PG (reference stoix/systems/ddpg/ff_d4pg.py, 720 LoC).

Distinctives: distributional critic over a fixed categorical support
(DistributionalContinuousQNetwork head) trained with the categorical
projection (categorical_td_learning on the bootstrapped support), deterministic
actor ascending the expected-Q.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import OnlineAndTarget, Transition
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import categorical_l2_project
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.ddpg.ff_ddpg import DDPGOptStates, DDPGParams
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def _build_networks(env: envs.Environment, config: Any):
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    action_space = env.action_space()
    action_dim = env.num_actions
    lo = float(jnp.min(jnp.asarray(action_space.low)))
    hi = float(jnp.max(jnp.asarray(action_space.high)))

    net_cfg = config.network
    actor = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head, action_dim=action_dim, minimum=lo, maximum=hi
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic = FeedForwardCritic(
        critic_head=config_lib.instantiate(
            net_cfg.critic_network.critic_head,
            num_atoms=int(config.system.get("num_atoms", 51)),
            vmin=float(config.system.get("vmin", -100.0)),
            vmax=float(config.system.get("vmax", 100.0)),
        ),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )
    return actor, critic, (lo, hi)


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array):
    actor, critic, (act_lo, act_hi) = _build_networks(env, config)
    config.system.action_dim = env.num_actions
    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    noise_sigma = float(config.system.get("exploration_sigma", 0.1))

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.q_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )

    key, actor_key, q_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    dummy_act = jnp.asarray(env.action_value(), jnp.float32)[None]
    actor_p = actor.init(actor_key, dummy_obs)
    q_p = critic.init(q_key, dummy_obs, dummy_act)
    params = DDPGParams(OnlineAndTarget(actor_p, actor_p), OnlineAndTarget(q_p, q_p))
    opt_states = DDPGOptStates(actor_optim.init(actor_p), q_optim.init(q_p))

    buffer, buffer_state = core.build_buffer(env, config, mesh)

    def q_loss_fn(q_online, obs, action, target_probs):
        _, logits, _ = critic.apply(q_online, obs, action)
        ce = -jnp.sum(target_probs * jax.nn.log_softmax(logits, axis=-1), axis=-1)
        loss = jnp.mean(ce)
        return loss, {"q_loss": loss}

    def actor_loss_fn(actor_online, q_online, obs):
        action = actor.apply(actor_online, obs).mode()
        q_value, _, _ = critic.apply(q_online, obs, action)
        loss = -jnp.mean(q_value)
        return loss, {"actor_loss": loss}

    def update_from_batch(params: DDPGParams, opt_states: DDPGOptStates, batch: Transition, key):
        next_action = actor.apply(params.actor_params.target, batch.next_obs).mode()
        _, next_logits, atoms = critic.apply(
            params.q_params.target, batch.next_obs, next_action
        )
        d_t = gamma * (1.0 - batch.done.astype(jnp.float32))
        target_z = batch.reward[:, None] + d_t[:, None] * atoms[None, :]
        target_probs = jax.lax.stop_gradient(
            categorical_l2_project(target_z, jax.nn.softmax(next_logits, axis=-1), atoms)
        )

        q_grads, q_metrics = jax.grad(q_loss_fn, has_aux=True)(
            params.q_params.online, batch.obs, batch.action, target_probs
        )
        q_grads = core.pmean_grads(q_grads)
        q_updates, q_opt_state = q_optim.update(q_grads, opt_states.q_opt_state)
        q_online = optax.apply_updates(params.q_params.online, q_updates)
        q_target = optax.incremental_update(q_online, params.q_params.target, tau)

        actor_grads, actor_metrics = jax.grad(actor_loss_fn, has_aux=True)(
            params.actor_params.online, q_online, batch.obs
        )
        actor_grads = core.pmean_grads(actor_grads)
        actor_updates, actor_opt_state = actor_optim.update(
            actor_grads, opt_states.actor_opt_state
        )
        actor_online = optax.apply_updates(params.actor_params.online, actor_updates)
        actor_target = optax.incremental_update(actor_online, params.actor_params.target, tau)

        new_params = DDPGParams(
            OnlineAndTarget(actor_online, actor_target), OnlineAndTarget(q_online, q_target)
        )
        return (new_params, DDPGOptStates(actor_opt_state, q_opt_state)), {
            **q_metrics, **actor_metrics,
        }

    def act_in_env(params: DDPGParams, observation, key, buffer_state=None):
        action = actor.apply(params.actor_params.online, observation).mode()
        noise = jax.random.normal(key, action.shape) * noise_sigma * (act_hi - act_lo) / 2
        return jnp.clip(action + noise, act_lo, act_hi)

    learn_per_shard = core.standard_off_policy_learner(
        env, buffer, config, update_from_batch, act_in_env
    )
    warmup_core_fn = core.get_random_warmup_fn(env, config, buffer.add)
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )
    learn, warmup = core.wrap_learn_and_warmup(learn_per_shard, warmup_core_fn, mesh, state_specs)

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params.online),
    )
    return setup, warmup


def run_experiment(config: Any) -> float:
    holder = {}

    def setup_fn(env, cfg, mesh, key):
        setup, warmup = learner_setup(env, cfg, mesh, key)
        holder["warmup"] = warmup
        return setup

    return run_anakin_experiment(config, setup_fn, warmup_fn=lambda s: holder["warmup"](s))


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_d4pg.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
