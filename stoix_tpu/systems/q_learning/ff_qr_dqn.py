"""Anakin QR-DQN (reference stoix/systems/q_learning/ff_qr_dqn.py, 602 LoC):
quantile-regression distributional Q-learning (quantile_q_learning, reference
stoix/utils/loss.py:268) with the QuantileDiscreteQNetwork head."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from stoix_tpu.base_types import Transition
from stoix_tpu.ops import losses
from stoix_tpu.systems.q_learning.q_family import run_q_experiment
from stoix_tpu.utils import config as config_lib


def qr_dqn_loss(online_params: Any, target_params: Any, batch: Transition, q_apply, config):
    _, dist_q_tm1, tau_tm1 = q_apply(online_params, batch.obs, 0.0)
    _, dist_q_t, _ = q_apply(target_params, batch.next_obs, 0.0)
    _, dist_q_t_selector, _ = q_apply(online_params, batch.next_obs, 0.0)
    d_t = float(config.system.gamma) * (1.0 - batch.done.astype(jnp.float32))
    loss = losses.quantile_q_learning(
        dist_q_tm1, tau_tm1, batch.action, batch.reward, d_t,
        dist_q_t_selector, dist_q_t,
        huber_param=float(config.system.get("huber_loss_parameter", 1.0)),
    )
    return loss, {"q_loss": loss}


def _head_kwargs(config: Any) -> dict:
    return dict(num_quantiles=int(config.system.get("num_quantiles", 51)))


def run_experiment(config: Any) -> float:
    return run_q_experiment(config, qr_dqn_loss, head_kwargs=_head_kwargs(config))


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_qr_dqn.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
