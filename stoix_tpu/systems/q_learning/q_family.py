"""Shared Anakin skeleton for the value-based (DQN) family.

The reference implements each variant as a near-identical 570-680 LoC file
(reference stoix/systems/q_learning/ff_{dqn,ddqn,dqn_reg,mdqn,c51,qr_dqn}.py);
the only real differences are the network HEAD and the LOSS. Each system file
supplies a `QLossFn` plus head kwargs; all scaffolding (buffer, sharding,
rollout/update loops) comes from off_policy_core.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import OffPolicyLearnerState, OnlineAndTarget, Transition
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.resilience import guards
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate

# (online_params, target_params, batch, q_apply, config) -> (loss, metrics)
QLossFn = Callable[[Any, Any, Transition, Callable, Any], Tuple[jax.Array, Dict]]


def act_dist(apply_out: Any):
    """Distribution from a head output (plain heads return the dist; the
    distributional heads return (dist, logits/quantiles, atoms/taus))."""
    return apply_out[0] if isinstance(apply_out, tuple) else apply_out


def get_discrete_warmup_fn(env: envs.Environment, config: Any, buffer_add: Callable) -> Callable:
    """Uniform-random discrete-action buffer fill (reference ff_dqn.py:37-89)."""

    def warmup(state: OffPolicyLearnerState) -> OffPolicyLearnerState:
        def _step(carry, _):
            env_state, timestep, key = carry
            key, act_key = jax.random.split(key)
            n_envs = timestep.reward.shape[0]
            action = jax.random.randint(act_key, (n_envs,), 0, int(config.system.action_dim))
            next_env_state, next_timestep = env.step(env_state, action)
            return (next_env_state, next_timestep, key), core.make_transition(
                timestep, action, next_timestep
            )

        key, warmup_key = jax.random.split(state.key)
        (env_state, timestep, _), traj = jax.lax.scan(
            _step, (state.env_state, state.timestep, warmup_key), None,
            int(config.system.warmup_steps),
        )
        buffer_state = buffer_add(state.buffer_state, tree_merge_leading_dims(traj, 2))
        return state._replace(
            buffer_state=buffer_state, key=key, env_state=env_state, timestep=timestep
        )

    return warmup


def build_q_network(config: Any, num_actions: int, **extra_head_kwargs: Any):
    from stoix_tpu.networks.base import FeedForwardActor

    net_cfg = config.network.actor_network
    head_kwargs = dict(
        action_dim=num_actions, epsilon=float(config.system.evaluation_epsilon)
    )
    head_kwargs.update(extra_head_kwargs)
    return FeedForwardActor(
        action_head=config_lib.instantiate(net_cfg.action_head, **head_kwargs),
        torso=config_lib.instantiate(net_cfg.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.input_layer),
    )


def q_learner_setup(
    env: envs.Environment,
    config: Any,
    mesh: Mesh,
    key: jax.Array,
    loss_fn: QLossFn,
    head_kwargs: Dict[str, Any] | None = None,
) -> Tuple[AnakinSetup, Callable]:
    num_actions = env.num_actions
    config.system.action_dim = num_actions
    tau = float(config.system.tau)
    train_eps = float(config.system.training_epsilon)
    final_eps = float(config.system.get("final_epsilon", train_eps))
    decay_steps = float(config.system.get("epsilon_decay_steps", 0) or 0)
    if decay_steps > 0 and final_eps == train_eps:
        raise ValueError(
            "system.epsilon_decay_steps is set but system.final_epsilon equals "
            "training_epsilon — the requested decay would be a no-op. Set "
            "system.final_epsilon (e.g. 0.05)."
        )

    q_network = build_q_network(config, num_actions, **(head_kwargs or {}))
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(
            make_learning_rate(float(config.system.q_lr), config, int(config.system.epochs)),
            eps=1e-5,
        ),
    )

    key, net_key, env_key = jax.random.split(key, 3)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    online_params = q_network.init(net_key, dummy_obs)
    params = OnlineAndTarget(online_params, online_params)
    opt_state = q_optim.init(online_params)

    buffer, buffer_state = core.build_buffer(env, config, mesh, discrete_actions=True)

    guard_mode = guards.resolve_mode(config)

    def update_from_batch(params: OnlineAndTarget, opt_states, batch: Transition, key):
        del key

        def wrapped_loss(online_params):
            return loss_fn(online_params, params.target, batch, q_network.apply, config)

        # value_and_grad instead of grad: the divergence guard needs the loss
        # VALUE; with update_guard=off the value is unused and XLA dead-code-
        # eliminates it (grad is itself a value_and_grad that drops the value,
        # so the traced program is unchanged).
        (loss, loss_info), grads = jax.value_and_grad(wrapped_loss, has_aux=True)(
            params.online
        )
        grads = core.pmean_grads(grads)
        updates, new_opt_states = q_optim.update(grads, opt_states)
        online = optax.apply_updates(params.online, updates)
        target = optax.incremental_update(online, params.target, tau)
        # Divergence guard (resilience/guards.py): no-op the whole
        # (params, opt_state) update when loss/grad-norm is non-finite.
        (guarded_params, guarded_opt), guard_metrics = guards.guard_update(
            guard_mode,
            new=(OnlineAndTarget(online, target), new_opt_states),
            old=(params, opt_states),
            loss=loss,
            grads=grads,
            opt_state=opt_states,
            axis_names=("batch", "data"),
            metric_axes=("batch",),
        )
        return (guarded_params, guarded_opt), {**loss_info, **guard_metrics}

    def act_in_env(params: OnlineAndTarget, observation, key, buffer_state=None):
        # Linear epsilon decay keyed on per-shard experience count (reference
        # systems anneal exploration; enabled via system.epsilon_decay_steps).
        if decay_steps > 0 and buffer_state is not None:
            frac = jnp.minimum(
                buffer_state.num_added.astype(jnp.float32) / decay_steps, 1.0
            )
            eps = train_eps + frac * (final_eps - train_eps)
        else:
            eps = train_eps
        dist = act_dist(q_network.apply(params.online, observation, eps))
        return dist.sample(seed=key)

    learn_per_shard = core.standard_off_policy_learner(
        env, buffer, config, update_from_batch, act_in_env
    )
    warmup_core_fn = get_discrete_warmup_fn(env, config, buffer.add)
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_state, buffer_state, key, env_key
    )
    learn, warmup = core.wrap_learn_and_warmup(
        learn_per_shard, warmup_core_fn, mesh, state_specs
    )

    def eval_apply(params, obs, *a, **kw):
        return act_dist(q_network.apply(params, obs, *a, **kw))

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.online),
    )
    return setup, warmup


def run_q_experiment(config: Any, loss_fn: QLossFn, head_kwargs: Dict[str, Any] | None = None) -> float:
    holder = {}

    def setup_fn(env, cfg, mesh, key):
        setup, warmup = q_learner_setup(env, cfg, mesh, key, loss_fn, head_kwargs)
        holder["warmup"] = warmup
        return setup

    return run_anakin_experiment(config, setup_fn, warmup_fn=lambda s: holder["warmup"](s))
