"""Anakin Rainbow (reference stoix/systems/q_learning/ff_rainbow.py, 676 LoC).

Distinctives preserved: prioritised trajectory buffer for n-step sequences
(reference ff_rainbow.py:433), noisy dueling distributional network
(reference dueling.py:90) driven by the "noise" rng stream, C51 projection
targets over n-step returns, importance-weighted loss + priority updates.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OffPolicyLearnerState, OnlineAndTarget
from stoix_tpu.buffers import make_prioritised_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import categorical_l2_project
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def get_learner_fn(env, q_network, q_update, buffer, config):
    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    n_step = int(config.system.get("n_step", 3))
    importance_beta = float(config.system.get("importance_sampling_exponent", 0.6))

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, act_key, noise_key = jax.random.split(key, 3)
        dist, _, _ = q_network.apply(
            params.online, last_timestep.observation, rngs={"noise": noise_key}
        )
        action = dist.sample(seed=act_key)
        env_state, timestep = env.step(env_state, action)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "next_obs": timestep.extras["next_obs"],
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(params, opt_states, buffer_state, key, env_state, timestep),
            data,
        )

    def _loss_fn(online_params, target_params, seq, probs, key):
        k1, k2, k3 = jax.random.split(key, 3)
        obs_0 = jax.tree.map(lambda x: x[:, 0], seq["obs"])
        action_0 = seq["action"][:, 0]
        # n-step discounted reward and terminal discount over the sequence.
        discounts = gamma * seq["discount"][:, :-1]  # [B, n]
        cum = jnp.cumprod(
            jnp.concatenate([jnp.ones_like(discounts[:, :1]), discounts[:, :-1]], axis=1),
            axis=1,
        )
        r_n = jnp.sum(cum * seq["reward"][:, :-1], axis=1)
        d_n = jnp.prod(discounts, axis=1)
        # Bootstrap state is s_n = obs of the LAST sequence element (rewards
        # and discounts above cover transitions 0..n-1 exactly).
        obs_n = jax.tree.map(lambda x: x[:, -1], seq["obs"])

        _, logits_0, atoms = q_network.apply(online_params, obs_0, rngs={"noise": k1})
        dist_sel, _, _ = q_network.apply(online_params, obs_n, rngs={"noise": k2})
        _, logits_n, _ = q_network.apply(target_params, obs_n, rngs={"noise": k3})
        best_a = jnp.argmax(dist_sel.preferences, axis=-1)

        num_atoms = atoms.shape[0]
        probs_best = jnp.take_along_axis(
            jax.nn.softmax(logits_n, axis=-1),
            best_a[:, None, None].repeat(num_atoms, -1), axis=-2,
        )[:, 0, :]
        target_z = r_n[:, None] + d_n[:, None] * atoms[None, :]
        target = jax.lax.stop_gradient(
            categorical_l2_project(target_z, probs_best, atoms)
        )
        logits_a = jnp.take_along_axis(
            logits_0, action_0[:, None, None].repeat(num_atoms, -1), axis=-2
        )[:, 0, :]
        ce = -jnp.sum(target * jax.nn.log_softmax(logits_a, axis=-1), axis=-1)  # [B]

        # Importance sampling weights (normalized to max 1).
        weights = (1.0 / jnp.maximum(probs, 1e-9)) ** importance_beta
        weights = weights / jnp.max(weights)
        loss = jnp.mean(weights * ce)
        return loss, (ce, {"q_loss": loss})

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key, loss_key = jax.random.split(key, 3)
        sample = buffer.sample(buffer_state, sample_key)
        grads, (ce, loss_info) = jax.grad(_loss_fn, has_aux=True)(
            params.online, params.target, sample.experience, sample.probabilities, loss_key
        )
        grads = core.pmean_grads(grads)
        updates, opt_states = q_update(grads, opt_states)
        online = optax.apply_updates(params.online, updates)
        target = optax.incremental_update(online, params.target, tau)
        buffer_state = buffer.set_priorities(buffer_state, sample.indices, ce)
        return (OnlineAndTarget(online, target), opt_states, buffer_state, key), loss_info

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        buffer_state = buffer.add(
            buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        )
        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array):
    from stoix_tpu.networks.base import FeedForwardActor

    config.system.action_dim = env.num_actions
    net_cfg = config.network.actor_network
    q_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.action_head,
            action_dim=env.num_actions,
            epsilon=float(config.system.evaluation_epsilon),
            num_atoms=int(config.system.get("num_atoms", 51)),
            vmin=float(config.system.get("vmin", -10.0)),
            vmax=float(config.system.get("vmax", 10.0)),
        ),
        torso=config_lib.instantiate(net_cfg.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.input_layer),
    )
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.q_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )

    key, net_key, env_key = jax.random.split(key, 3)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    online = q_network.init({"params": net_key, "noise": net_key}, dummy_obs)
    params = OnlineAndTarget(online, online)
    opt_state = q_optim.init(online)

    n_step = int(config.system.get("n_step", 3))
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_prioritised_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=n_step + 1,
        period=1,
        max_length_time_axis=max_length,
        priority_exponent=float(config.system.get("priority_exponent", 0.6)),
    )
    dummy_item = {
        "obs": env.observation_value(),
        "action": jnp.zeros((), jnp.int32),
        "reward": jnp.zeros((), jnp.float32),
        "discount": jnp.zeros((), jnp.float32),
        "next_obs": env.observation_value(),
    }
    buffer_state = buffer.init(dummy_item)

    learn_per_shard = get_learner_fn(env, q_network, q_optim.update, buffer, config)
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_state, buffer_state, key, env_key
    )

    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    # Rainbow's warmup writes trajectory-layout sequences (not flat items).
    def traj_warmup(state):
        def _step(carry, _):
            env_state, timestep, key = carry
            key, act_key = jax.random.split(key)
            n_envs = timestep.reward.shape[0]
            action = jax.random.randint(act_key, (n_envs,), 0, env.num_actions)
            next_env_state, next_timestep = env.step(env_state, action)
            data = {
                "obs": timestep.observation,
                "action": action,
                "reward": next_timestep.reward,
                "discount": next_timestep.discount,
                "next_obs": next_timestep.extras["next_obs"],
            }
            return (next_env_state, next_timestep, key), data

        key, warmup_key = jax.random.split(state.key)
        (env_state, timestep, _), traj = jax.lax.scan(
            _step, (state.env_state, state.timestep, warmup_key), None,
            int(config.system.warmup_steps),
        )
        buffer_state = buffer.add(
            state.buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), traj)
        )
        return state._replace(
            buffer_state=buffer_state, key=key, env_state=env_state, timestep=timestep
        )

    def per_shard_warmup(state):
        squeezed = state._replace(
            buffer_state=jax.tree.map(lambda x: x[0], state.buffer_state),
            key=state.key[0],
        )
        out = jax.vmap(traj_warmup, axis_name="batch")(squeezed)
        return out._replace(
            buffer_state=jax.tree.map(lambda x: x[None], out.buffer_state),
            key=out.key[None],
        )

    warmup = jax.jit(
        shard_map(
            per_shard_warmup, mesh=mesh, in_specs=(state_specs,),
            out_specs=state_specs, check_vma=False,
        )
    )

    def eval_apply(params, obs):
        dist, _, _ = q_network.apply(params, obs)
        return dist

    setup = AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.online),
    )
    return setup, warmup


def run_experiment(config: Any) -> float:
    holder = {}

    def setup_fn(env, cfg, mesh, key):
        setup, warmup = learner_setup(env, cfg, mesh, key)
        holder["warmup"] = warmup
        return setup

    return run_anakin_experiment(config, setup_fn, warmup_fn=lambda s: holder["warmup"](s))


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_rainbow.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
