"""Anakin PQN (reference stoix/systems/q_learning/ff_pqn.py, 519 LoC):
buffer-free parallel Q-learning — epsilon-greedy rollouts, Q(lambda) targets
over the fresh trajectory (reference ff_pqn.py:114-118), epoch/minibatch SGD
like PPO. The reference pairs it with a LayerNorm MLP torso.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OnPolicyLearnerState
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import q_lambda
from stoix_tpu.systems import anakin
from stoix_tpu.systems.q_learning.q_family import build_q_network
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class PQNStepCount(NamedTuple):
    """Dedicated gradient-step counter state, found by type (not by leaf-name
    pattern matching) so future optimizer-chain changes can't silently change
    the epsilon annealing rate."""

    count: jax.Array


def count_gradient_steps() -> optax.GradientTransformation:
    """Stateful no-op transform appended to the PQN chain: its PQNStepCount
    increments exactly once per gradient step."""

    def init(params):
        del params
        return PQNStepCount(jnp.zeros((), jnp.int32))

    def update(updates, state, params=None):
        del params
        return updates, PQNStepCount(state.count + 1)

    return optax.GradientTransformation(init, update)


def _find_step_count(opt_states) -> jax.Array:
    counts = [
        leaf.count
        for leaf in jax.tree.leaves(
            opt_states, is_leaf=lambda x: isinstance(x, PQNStepCount)
        )
        if isinstance(leaf, PQNStepCount)
    ]
    assert len(counts) == 1, "expected exactly one PQNStepCount in the optimizer chain"
    return counts[0]


def get_learner_fn(env, q_apply, q_update, config):
    gamma = float(config.system.gamma)
    lam = float(config.system.get("q_lambda", 0.65))
    train_eps = float(config.system.training_epsilon)
    # Reference PQN anneals epsilon 1.0 -> training_epsilon over
    # exploration_fraction of training (reference
    # configs/system/q_learning/ff_pqn.yaml decay_epsilon/exploration_fraction).
    # PQN is buffer-free, so progress is read off the dedicated step counter.
    decay = bool(config.system.get("decay_epsilon", False))
    explore_frac = float(config.system.get("exploration_fraction", 0.5))
    grad_steps_per_update = int(config.system.epochs) * int(config.system.num_minibatches)
    decay_updates = max(1.0, explore_frac * int(config.arch.num_updates))

    def _epsilon(opt_states):
        if not decay:
            return train_eps
        count = _find_step_count(opt_states)
        frac = jnp.minimum(
            count.astype(jnp.float32) / grad_steps_per_update / decay_updates, 1.0
        )
        return 1.0 + frac * (train_eps - 1.0)

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, act_key = jax.random.split(key)
        dist = q_apply(params, last_timestep.observation, _epsilon(opt_states))
        action = dist.sample(seed=act_key)
        env_state, timestep = env.step(env_state, action)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "truncated": jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            "next_obs": timestep.extras["next_obs"],
            "info": timestep.extras["episode_metrics"],
        }
        return OnPolicyLearnerState(params, opt_states, key, env_state, timestep), data

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        # Q(lambda) targets over the fresh trajectory, time-major. q_next is
        # computed from the TRUE next obs, so forcing lambda_t = 0 at
        # truncations bootstraps from it instead of chaining the return across
        # the auto-reset boundary; terminations are cut by discount = 0.
        q_next = q_apply(params, traj["next_obs"], 0.0).preferences  # [T, E, A]
        lam_t = lam * (1.0 - traj["truncated"].astype(jnp.float32))
        targets = q_lambda(
            traj["reward"], gamma * traj["discount"], q_next, lam_t, batch_major=False
        )

        def _update_epoch(carry, _):
            params, opt_states, key = carry
            key, shuffle_key = jax.random.split(key)
            batch_size = targets.shape[0] * targets.shape[1]
            perm = jax.random.permutation(shuffle_key, batch_size)
            flat = tree_merge_leading_dims((traj["obs"], traj["action"], targets), 2)
            shuffled = jax.tree.map(lambda x: jnp.take(x, perm, axis=0), flat)
            minibatches = jax.tree.map(
                lambda x: x.reshape((int(config.system.num_minibatches), -1) + x.shape[1:]),
                shuffled,
            )

            def _update_minibatch(carry, batch):
                params, opt_states = carry
                obs, action, target = batch

                def loss_fn(p):
                    q = q_apply(p, obs, 0.0).preferences
                    qa = jnp.take_along_axis(q, action[..., None], axis=-1)[..., 0]
                    loss = 0.5 * jnp.mean((qa - target) ** 2)
                    return loss, {"q_loss": loss, "mean_q": jnp.mean(q)}

                grads, metrics = jax.grad(loss_fn, has_aux=True)(params)
                grads = jax.lax.pmean(grads, axis_name="batch")
                grads = jax.lax.pmean(grads, axis_name="data")
                updates, opt_states = q_update(grads, opt_states)
                params = optax.apply_updates(params, updates)
                return (params, opt_states), metrics

            (params, opt_states), metrics = jax.lax.scan(
                _update_minibatch, (params, opt_states), minibatches
            )
            return (params, opt_states, key), metrics

        (params, opt_states, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, key), None, int(config.system.epochs)
        )
        learner_state = OnPolicyLearnerState(params, opt_states, key, env_state, last_timestep)
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    config.system.action_dim = env.num_actions
    q_network = build_q_network(config, env.num_actions)
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.radam(make_learning_rate(float(config.system.q_lr), config,
                                       int(config.system.epochs),
                                       int(config.system.num_minibatches))),
        count_gradient_steps(),
    )

    key, net_key, env_key = jax.random.split(key, 3)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    params = q_network.init(net_key, dummy_obs)
    opt_state = q_optim.init(params)

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_state, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(env, q_network.apply, q_optim.update, config)
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, q_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_pqn.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
