"""Sebulba DQN — the off-policy ingestion path (docs/DESIGN.md §2.10).

Actor devices run epsilon-greedy inference against stateful envs and PUSH
transition shards through the OffPolicyPipeline whenever a rollout chunk is
ready; learner devices own a device-resident sharded replay service
(stoix_tpu/replay) and SAMPLE it independently — no lockstep collect, so a
slow or supervisor-restarting actor never stalls the learner (Podracer's
actor/learner core split, arxiv 2104.06272, applied to the DQN family).

Data path per ingest: actors flatten a [T, E] rollout chunk to [T*E]
transitions, split it across learner devices, and device_put the shards
directly onto their owning devices; the learner assembles each payload into
ONE global array via parallel.assemble_global_array (no host concat) and
hands it to `service.add` — raw experience lands on its shard and never
moves again. The learn step is one jitted shard_map program embedding the
replay core's cross-shard sampler: sample (a psum of the drawn minibatch is
the only experience bytes on the interconnect) -> Q-learning update ->
polyak target sync, with optional prioritized replay (per-TD-error
priorities scattered back through global indices, importance weights from
the GLOBAL sampling probabilities).

Supervision/heartbeats are the standard Sebulba set: actor threads are
owned by the ActorSupervisor (crash -> bounded-backoff restart with a fresh
env + re-primed params; budget exhausted -> typed ComponentFailure through
the pipeline), every push beats the HeartbeatBoard, and a starved learner
raises ActorStarvationError naming the stalest actor.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from typing import Any, List, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from stoix_tpu.base_types import OnlineAndTarget, Transition
from stoix_tpu.envs.factory import make_factory
from stoix_tpu.evaluator import get_distribution_act_fn, get_ff_evaluator_fn
from stoix_tpu.observability import (
    RunStats,
    flightrec,
    get_health_monitor,
    get_logger,
    get_registry,
    get_status_board,
    goodput,
    span,
)
from stoix_tpu.parallel import MeshRoles, assemble_global_array
from stoix_tpu.parallel.mesh import shard_map
from stoix_tpu.replay import ShardedReplayService, service_from_config
from stoix_tpu.resilience import (
    PreemptionHandler,
    faultinject,
    guards,
    supervisor_from_config,
)
from stoix_tpu.resilience.errors import EvaluatorStallError
from stoix_tpu.sebulba.core import (
    AsyncEvaluator,
    OffPolicyPipeline,
    ParameterServer,
    ThreadLifetime,
)
from stoix_tpu.systems.q_learning.q_family import act_dist, build_q_network
from stoix_tpu.utils import compilecache
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.logger import LogEvent, StoixLogger
from stoix_tpu.utils.timing import TimingTracker
from stoix_tpu.utils.training import make_learning_rate

# Stats of the most recent run_experiment call in this process (read by
# bench.py --replay / tests); registry series are the source of truth.
LAST_RUN_STATS = RunStats()


class DQNLearnerState(NamedTuple):
    params: OnlineAndTarget
    opt_state: Any
    key: jax.Array


def get_dqn_learn_step(
    q_apply, q_update, config: Any, mesh: Mesh, service: ShardedReplayService
):
    """One jitted shard_map program per update: sample the sharded replay
    where the data lives, Q-learning step, polyak target sync. The replay
    state threads through (donated — the ring is the device's largest
    allocation) so prioritized runs scatter fresh priorities in-program."""
    core = service.core
    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    epochs = int(config.system.epochs)
    replay_cfg = dict(config.system.get("replay") or {})
    prioritized = bool(replay_cfg.get("prioritized", False))
    beta = float(replay_cfg.get("importance_beta", 0.4))
    guard_mode = guards.resolve_mode(config)

    def per_shard(state: DQNLearnerState, replay_state):
        rstate = jax.tree.map(lambda x: x[0], replay_state)

        def _epoch(carry, _):
            state, rstate = carry
            key, sample_key = jax.random.split(state.key)
            # state.key is replicated (in_specs P()), so every shard draws
            # the same uniforms — the core's ownership-partition contract.
            drawn = core.sample(rstate, sample_key)
            batch: Transition = drawn.experience

            if prioritized:
                # PER importance weights from the GLOBAL sampling
                # probabilities (the psum'd normalization), so the
                # correction is exact however mass is spread over shards.
                # A zero-probability row (zeroed priority resampled before
                # its slot was overwritten) contributes NOTHING — the
                # (N*p)^-beta form would instead hand it the batch's
                # LARGEST weight and flatten every real row to ~0 through
                # the max-normalization.
                n_global = jax.lax.psum(core.occupancy(rstate), "data")
                w = jnp.where(
                    drawn.probabilities > 0,
                    jnp.power(
                        jnp.maximum(n_global.astype(jnp.float32), 1.0)
                        * jnp.maximum(drawn.probabilities, 1e-9),
                        -beta,
                    ),
                    0.0,
                )
                w = w / jnp.maximum(jax.lax.pmax(jnp.max(w), "data"), 1e-9)
            else:
                w = jnp.ones_like(batch.reward)

            def loss_fn(online):
                q_tm1 = q_apply(online, batch.obs, 0.0).preferences
                q_t = q_apply(state.params.target, batch.next_obs, 0.0).preferences
                d_t = gamma * (1.0 - batch.done.astype(jnp.float32))
                target = batch.reward + d_t * jnp.max(q_t, axis=-1)
                qa = jnp.take_along_axis(
                    q_tm1, batch.action.astype(jnp.int32)[:, None], axis=-1
                )[:, 0]
                td = jax.lax.stop_gradient(target) - qa
                loss = 0.5 * jnp.mean(w * jnp.square(td))
                return loss, (td, jnp.mean(q_tm1))

            (loss, (td, mean_q)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                state.params.online
            )
            grads = jax.lax.pmean(grads, axis_name="data")
            updates, opt_state = q_update(grads, state.opt_state)
            online = optax.apply_updates(state.params.online, updates)
            target = optax.incremental_update(online, state.params.target, tau)
            (params, opt_state), guard_metrics = guards.guard_update(
                guard_mode,
                new=(OnlineAndTarget(online, target), opt_state),
                old=(state.params, state.opt_state),
                loss=loss,
                grads=grads,
                opt_state=state.opt_state,
                axis_names=("data",),
            )
            if prioritized:
                rstate = core.set_priorities(rstate, drawn.indices, jnp.abs(td))
            metrics = {"q_loss": loss, "mean_q": mean_q, **guard_metrics}
            return (DQNLearnerState(params, opt_state, key), rstate), metrics

        (state, rstate), metrics = jax.lax.scan(
            _epoch, (state, rstate), None, epochs
        )
        metrics = jax.lax.pmean(metrics, axis_name="data")
        return state, jax.tree.map(lambda x: x[None], rstate), metrics

    return jax.jit(
        shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(P(), P("data")),
            out_specs=(P(), P("data"), P()),
            check_vma=False,
        ),
        donate_argnums=(1,),
    )


def rollout_thread(
    actor_id: int,
    actor_device: jax.Device,
    env_factory,
    q_apply,
    config: Any,
    pipeline: OffPolicyPipeline,
    param_server: ParameterServer,
    learner_devices: List[jax.Device],
    lifetime: ThreadLifetime,
    seed: int,
    metrics_sink: "queue.Queue",
    supervisor: Any = None,
) -> None:
    try:
        _rollout_body(
            actor_id, actor_device, env_factory, q_apply, config, pipeline,
            param_server, learner_devices, lifetime, seed, metrics_sink,
        )
    except Exception as exc:
        import traceback

        get_registry().counter(
            "stoix_tpu_sebulba_actor_crashes_total",
            "Actor threads that died with an exception",
        ).inc(labels={"actor": str(actor_id)})
        get_logger("stoix_tpu.sebulba").error(
            "[actor-%d] CRASHED:\n%s", actor_id, traceback.format_exc()
        )
        if supervisor is not None:
            supervisor.report_crash(actor_id, exc)
        else:
            lifetime.stop()


def _rollout_body(
    actor_id, actor_device, env_factory, q_apply, config, pipeline,
    param_server, learner_devices, lifetime, seed, metrics_sink,
):
    envs_per_actor = int(config.arch.actor.envs_per_actor)
    rollout_length = int(config.system.rollout_length)
    train_eps = float(config.system.training_epsilon)
    timer = TimingTracker()
    envs = env_factory(envs_per_actor)
    timestep = envs.reset(seed=seed)

    @jax.jit
    def act_fn(params, observation, key):
        dist = act_dist(q_apply(params, observation, train_eps))
        return dist.sample(seed=key)

    with jax.default_device(actor_device):
        key = jax.random.PRNGKey(seed)
        params = param_server.get_params(actor_id)
        n_learners = len(learner_devices)
        rollout_idx = 0
        while not lifetime.should_stop():
            faultinject.maybe_crash_actor(actor_id, rollout_idx)
            faultinject.maybe_stall_queue(
                actor_id, rollout_idx, should_abort=lifetime.should_stop
            )
            if rollout_idx > 0:
                # Off-policy actors NEVER wait for params: grab a fresh
                # version when one is queued, otherwise keep acting on the
                # current one (staleness is the architecture's contract).
                try:
                    fetched = param_server.get_params(actor_id, timeout=0.0)
                    if fetched is None:
                        break
                    params = fetched
                except queue.Empty:
                    pass
            traj: List[Transition] = []
            ep_infos: List[Any] = []
            with span("actor_rollout", actor=actor_id, idx=rollout_idx), \
                    timer.time("rollout"):
                for _ in range(rollout_length):
                    key, act_key = jax.random.split(key)
                    with timer.time("inference"):
                        obs_local = jax.device_put(timestep.observation, actor_device)
                        action = act_fn(params, obs_local, act_key)
                    with timer.time("env_step"):
                        next_timestep = envs.step(action)
                    traj.append(
                        Transition(
                            obs=obs_local,
                            action=action,
                            reward=next_timestep.reward,
                            done=next_timestep.discount == 0.0,
                            next_obs=next_timestep.extras["next_obs"],
                            # Episode metrics travel via metrics_sink, not
                            # through replay HBM.
                            info={},
                        )
                    )
                    ep_infos.append(next_timestep.extras["episode_metrics"])
                    timestep = next_timestep

            with span("actor_prepare_data", actor=actor_id), timer.time("prepare_data"):
                # [T, E] -> [T*E] transitions -> one shard per learner
                # device, placed directly on its owner for global-array
                # assembly (leading-axis sharding, no host concat).
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *traj)
                flat = jax.tree.map(
                    lambda x: x.reshape((-1,) + x.shape[2:]), stacked
                )
                payload = jax.tree.map(
                    lambda x: [
                        jax.device_put(s, d)
                        for s, d in zip(jnp.split(x, n_learners, axis=0), learner_devices)
                    ],
                    flat,
                )
            with timer.time("queue_put"):
                try:
                    pipeline.push(actor_id, payload, timeout=60.0)
                except queue.Full:
                    if lifetime.should_stop():
                        break
                    raise
            metrics_sink.put(
                {
                    "episode_metrics": jax.tree.map(
                        lambda *xs: np.stack([np.asarray(x) for x in xs]), *ep_infos
                    ),
                    "timings": {
                        **timer.all_means(prefix=f"actor{actor_id}_"),
                        **timer.all_percentiles(prefix=f"actor{actor_id}_"),
                    },
                }
            )
            rollout_idx += 1


def run_experiment(config: Any) -> float:
    LAST_RUN_STATS.clear()
    faultinject.configure(config.arch.get("fault_spec"))
    guard_mode = guards.resolve_mode(config)
    compilecache.configure(config)

    # One validated MeshRoles object replaces the ad-hoc device-id split
    # (parallel/roles.py, docs/DESIGN.md §2.11); the learn mesh it yields is
    # also what the sharded replay service's data axis lives on below.
    roles = MeshRoles.from_config(config)
    actor_devices = roles.role_devices("act")
    learner_devices = roles.role_devices("learn")
    evaluator_device = roles.device("evaluate")
    learner_mesh = roles.learn_mesh()
    eval_mesh = roles.role_mesh("evaluate")

    actors_per_device = int(config.arch.actor.actor_per_device)
    num_actors = len(actor_devices) * actors_per_device
    config.arch.actor.envs_per_actor = int(config.arch.total_num_envs) // num_actors
    chunk = int(config.arch.actor.envs_per_actor) * int(config.system.rollout_length)
    if chunk % len(learner_devices) != 0:
        raise ValueError(
            f"envs_per_actor * rollout_length ({chunk}) must divide over "
            f"{len(learner_devices)} learner device(s) for shard-wise ingestion"
        )

    steps_per_update = int(config.system.rollout_length) * int(config.arch.total_num_envs)
    if config.arch.get("num_updates") in (None, "~"):
        config.arch.num_updates = max(
            1, int(float(config.arch.total_timesteps)) // steps_per_update
        )
    config.arch.total_timesteps = int(config.arch.num_updates) * steps_per_update
    num_evaluation = max(1, int(config.arch.get("num_evaluation", 1)))
    config.arch.num_updates_per_eval = max(1, int(config.arch.num_updates) // num_evaluation)
    config.logger.system_name = config.system.system_name

    env_factory = make_factory(config)
    probe_envs = env_factory(1)
    num_actions = probe_envs.num_actions
    config.system.action_dim = num_actions

    q_network = build_q_network(config, num_actions)
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(
            make_learning_rate(float(config.system.q_lr), config, int(config.system.epochs)),
            eps=1e-5,
        ),
    )
    key = jax.random.PRNGKey(int(config.arch.seed))
    key, net_key, learn_key = jax.random.split(key, 3)
    obs0 = jax.tree.map(lambda x: jnp.asarray(x), probe_envs.reset(seed=0).observation)
    online_params = q_network.init(net_key, obs0)
    params = OnlineAndTarget(online_params, online_params)
    opt_state = q_optim.init(online_params)
    learner_state = jax.device_put(
        DQNLearnerState(params, opt_state, learn_key),
        NamedSharding(learner_mesh, P()),
    )

    # Replay service: buffer state sharded across learner HBM. The item
    # prototype is one UNBATCHED transition from the probe env.
    obs_single = jax.tree.map(lambda x: x[0], obs0)
    item = Transition(
        obs=obs_single,
        action=jnp.zeros((), jnp.int32),
        reward=jnp.zeros((), jnp.float32),
        done=jnp.zeros((), bool),
        next_obs=obs_single,
        info={},
    )
    service = service_from_config(learner_mesh, item, config)
    if service is None:
        raise ValueError(
            "Sebulba ff_dqn ingests through the sharded replay service: set "
            "system.replay.impl=sharded (the local item buffer lives inside "
            "Anakin's jitted learner and has no ingestion seam)"
        )
    replay_base = service.stats()

    learn_step = get_dqn_learn_step(
        q_network.apply, q_optim.update, config, learner_mesh, service
    )

    eval_eps = float(config.system.evaluation_epsilon)

    def eval_apply(p, observation):
        return act_dist(q_network.apply(p, observation, eval_eps))

    from stoix_tpu.envs import suites
    from stoix_tpu.envs.registry import ENV_REGISTRY, make_single
    from stoix_tpu.envs.wrappers import RecordEpisodeMetrics
    from stoix_tpu.evaluator import get_stateful_evaluator_fn

    scenario = (
        config.env.scenario.name
        if hasattr(config.env.scenario, "name")
        else config.env.scenario
    )
    suite = getattr(config.env, "env_name", None)
    if scenario in ENV_REGISTRY or suite in suites.SUITE_MAKERS:
        eval_env = RecordEpisodeMetrics(
            make_single(scenario, suite=suite, **dict(config.env.get("kwargs", {}) or {}))
        )
        eval_fn = get_ff_evaluator_fn(
            eval_env, get_distribution_act_fn(config, eval_apply), config, eval_mesh
        )
    else:
        eval_fn = get_stateful_evaluator_fn(
            env_factory, get_distribution_act_fn(config, eval_apply), config
        )

    logger = StoixLogger(config)
    # Ops plane (docs/DESIGN.md §2.13): register this run's identity, goodput
    # ledger, and heartbeat board on the instances configure() just reset.
    http_cfg = dict(dict(config.logger.get("telemetry") or {}).get("http") or {})
    ledger = goodput.GoodputLedger().start()
    goodput.set_active(ledger)
    recorder = flightrec.get_flight_recorder()
    recorder.set_context(
        architecture="sebulba",
        system=str(config.system.system_name),
        seed=int(config.arch.seed),
    )
    status = get_status_board()
    status.update(
        {
            "run_id": f"{config.system.system_name}_seed{config.arch.seed}",
            "architecture": "sebulba",
            "system": str(config.system.system_name),
            "step": 0,
        }
    )
    lifetime = ThreadLifetime()
    pipeline = OffPolicyPipeline(num_actors)
    monitor = get_health_monitor()
    monitor.register_board(
        "sebulba-pipeline",
        pipeline.heartbeats,
        stale_after_s=float(http_cfg.get("stale_after_s", 60.0) or 60.0),
    )
    param_server = ParameterServer(
        actor_devices, actors_per_device, heartbeats=pipeline.heartbeats
    )
    metrics_sink: "queue.Queue" = queue.Queue()
    eval_results: List[float] = []

    def on_eval_result(metrics, params_used, t):
        logger.log(metrics, t, len(eval_results), LogEvent.EVAL)
        eval_results.append(float(jnp.mean(metrics["episode_return"])))

    async_evaluator = AsyncEvaluator(
        eval_fn, lifetime, on_eval_result, heartbeats=pipeline.heartbeats
    )
    async_evaluator.thread.start()
    param_server.distribute_params(params.online)

    supervisor = supervisor_from_config(config, lifetime, pipeline, param_server)
    actor_threads: List[threading.Thread] = []

    def _actor_factory(actor_id: int, device):
        def make() -> threading.Thread:
            return threading.Thread(
                target=rollout_thread,
                args=(
                    actor_id, device, env_factory, q_network.apply, config,
                    pipeline, param_server, learner_devices, lifetime,
                    int(config.arch.seed) + 7919 * actor_id, metrics_sink,
                    supervisor,
                ),
                name=f"actor-{actor_id}",
                daemon=True,
            )

        return make

    for d_idx, device in enumerate(actor_devices):
        for a_idx in range(actors_per_device):
            actor_id = d_idx * actors_per_device + a_idx
            factory = _actor_factory(actor_id, device)
            if supervisor is not None:
                supervisor.register(actor_id, factory)
            else:
                t = factory()
                t.start()
                actor_threads.append(t)
    if supervisor is not None:
        supervisor.start_watchdog(pipeline.heartbeats)

    def _ingest(payloads) -> None:
        """Assemble each pushed payload into ONE global array per leaf
        (shards already sit on their owning learner devices) and add."""
        for _actor_id, payload in payloads:
            flat, treedef = jax.tree.flatten(
                payload, is_leaf=lambda x: isinstance(x, list)
            )
            merged = [
                assemble_global_array(leaf, learner_mesh, axis="data")
                if len(leaf) > 1
                else leaf[0]
                for leaf in flat
            ]
            service.add(jax.tree.unflatten(treedef, merged))

    preempt = PreemptionHandler().install()
    timer = TimingTracker()
    param_sync = max(1, int(dict(config.system.get("replay") or {}).get(
        "param_sync_interval", 1
    )))
    skipped_base = guards.skipped_counter().value()
    steady_start_time = None
    steady_start_items = 0
    steady_end_time = None
    preempted = False

    def ingested_items() -> int:
        return service.stats()["added_items"] - replay_base["added_items"]

    # Host-side episode-metric accumulation: drained from the sink EVERY
    # update (the sink is unbounded — letting rollout chunks pile up for a
    # whole inter-eval window grows host memory with run length), logged
    # and cleared at eval boundaries.
    pending_returns: List[float] = []
    pending_timings: dict = {}

    def _drain_metrics() -> None:
        while not metrics_sink.empty():
            m = metrics_sink.get_nowait()
            em = m["episode_metrics"]
            mask = em["is_terminal_step"].reshape(-1)
            if mask.any():
                pending_returns.extend(
                    em["episode_return"].reshape(-1)[mask].tolist()
                )
            pending_timings.update(m["timings"])

    replay_warmed = False
    try:
        for update_idx in range(int(config.arch.num_updates)):
            with timer.time("ingest"):
                _ingest(pipeline.poll(timeout=0.0))
                # can_sample is monotonic (fill only grows), so the jitted
                # psum + host fetch runs only until the first True.
                while not replay_warmed and not service.can_sample():
                    # Warmup/starvation path: block for more experience (a
                    # dead actor fleet raises typed starvation here).
                    _ingest(pipeline.wait_for_data(timeout=180.0))
                replay_warmed = True
            ledger.note(
                goodput.SEBULBA_PHASE_MAP["ingest"], timer.latest("ingest")
            )
            with span("learner_update", update=update_idx), timer.time("learn"):
                learner_state, new_replay, train_metrics = learn_step(
                    learner_state, service.state
                )
                service.commit(new_replay)
                service.note_embedded_samples(int(config.system.epochs))
                jax.block_until_ready(train_metrics)
            ledger.note(goodput.SEBULBA_PHASE_MAP["learn"], timer.latest("learn"))
            if (update_idx + 1) % param_sync == 0:
                param_server.distribute_params(learner_state.params.online)
            t_steps = ingested_items()
            guards.publish_guard_metrics(guard_mode, train_metrics, t_steps)
            _drain_metrics()
            if preempt.stop_requested():
                preempt.acknowledge(t_steps)
                preempted = True
                break

            if (update_idx + 1) % int(config.arch.num_updates_per_eval) == 0:
                ep_returns, timings = pending_returns, pending_timings
                pending_returns, pending_timings = [], {}
                if ep_returns:
                    logger.log({"episode_return": np.asarray(ep_returns)}, t_steps,
                               update_idx, LogEvent.ACT)
                logger.log(jax.tree.map(lambda x: jnp.mean(x), train_metrics),
                           t_steps, update_idx, LogEvent.TRAIN)
                logger.log(
                    {
                        **timings,
                        **timer.all_means(prefix="learner_"),
                        **timer.all_percentiles(prefix="learner_"),
                        **{f"replay_{k}": v for k, v in service.observe().items()
                           if not isinstance(v, list)},
                    },
                    t_steps, update_idx, LogEvent.MISC,
                )
                key, ek = jax.random.split(key)
                eval_params = jax.device_put(
                    jax.tree.map(np.asarray, learner_state.params.online),
                    evaluator_device,
                )
                async_evaluator.submit(eval_params, ek, t_steps)
                window_idx = (update_idx + 1) // int(config.arch.num_updates_per_eval)
                status.update({"window": window_idx, "step": t_steps})
                recorder.record(
                    "window", window=window_idx, step=t_steps,
                    updates=update_idx + 1,
                    queue_wait_s=round(timer.mean("ingest"), 6),
                    learn_s=round(timer.mean("learn"), 6),
                )
                if steady_start_time is None:
                    steady_start_time = time.perf_counter()
                    steady_start_items = ingested_items()
        steady_end_time = time.perf_counter()
    finally:
        preempt.uninstall()
        goodput.set_active(None)
        monitor.unregister("sebulba-pipeline")
        lifetime.stop()
        param_server.shutdown()
        for _ in range(2):
            if pipeline.drain(timeout=0.5) == 0:
                break
        if supervisor is not None:
            supervisor.join_all(timeout=10.0)
        for t in actor_threads:
            t.join(timeout=10.0)
        failure_propagating = sys.exc_info()[0] is not None
        try:
            async_evaluator.wait_until_idle(timeout=120.0)
        except EvaluatorStallError:
            if not failure_propagating:
                raise
            get_logger("stoix_tpu.sebulba").error(
                "[shutdown] evaluator still busy while handling another "
                "failure — dropping its in-flight work"
            )

    final_items = ingested_items()
    if (
        steady_start_time is not None
        and steady_end_time is not None
        and final_items > steady_start_items
        and steady_end_time > steady_start_time
    ):
        steady = (final_items - steady_start_items) / (
            steady_end_time - steady_start_time
        )
        get_registry().gauge(
            "stoix_tpu_sebulba_steps_per_sec_steady",
            "Post-compile steady-state env-steps/sec of the most recent run",
        ).set(steady)
        LAST_RUN_STATS["steps_per_sec_steady"] = steady
    replay_stats = service.stats()
    LAST_RUN_STATS["replay"] = {
        k: replay_stats[k] - replay_base[k] for k in replay_stats
    }
    LAST_RUN_STATS["goodput"] = ledger.finalize()
    LAST_RUN_STATS["resilience"] = {
        "update_guard": guard_mode,
        "skipped_updates": guards.skipped_counter().value() - skipped_base,
        "actor_restarts": supervisor.restart_count() if supervisor is not None else 0,
        "preempted": preempted,
        "resume_capable": False,
        "fleet": False,
    }
    logger.close()
    return eval_results[-1] if eval_results else 0.0


def main() -> float:
    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/sebulba/default_ff_dqn.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
