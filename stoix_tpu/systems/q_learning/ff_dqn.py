"""Anakin DQN (reference stoix/systems/q_learning/ff_dqn.py, 577 LoC).

Distinctives: item buffer sharded per (shard, update-batch) slice (reference
ff_dqn.py:325-345), warmup fill (:37-89), polyak target update (:207),
OnlineAndTarget params, EpsilonGreedy head. Skeleton in q_family.py.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from stoix_tpu.base_types import Transition
from stoix_tpu.ops import losses
from stoix_tpu.systems.q_learning.q_family import run_q_experiment
from stoix_tpu.utils import config as config_lib


def dqn_loss(online_params: Any, target_params: Any, batch: Transition, q_apply, config):
    q_tm1 = q_apply(online_params, batch.obs, 0.0).preferences
    q_t = q_apply(target_params, batch.next_obs, 0.0).preferences
    d_t = float(config.system.gamma) * (1.0 - batch.done.astype(jnp.float32))
    loss = losses.q_learning(
        q_tm1,
        batch.action,
        batch.reward,
        d_t,
        q_t,
        use_huber=bool(config.system.get("use_huber", False)),
        huber_delta=float(config.system.get("huber_loss_parameter", 1.0)),
    )
    return loss, {"q_loss": loss, "mean_q": jnp.mean(q_tm1)}


def run_experiment(config: Any) -> float:
    return run_q_experiment(config, dqn_loss)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_dqn.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
