"""Anakin R2D2 (reference stoix/systems/q_learning/rec_r2d2.py, 894 LoC — the
reference's largest Q-system).

Distinctives preserved: prioritised SEQUENCE replay with stored recurrent
states (reference :644), burn-in split to re-warm hidden states before the
training segment (:300-302), double-Q with a target network, transformed
n-step targets with the signed-hyperbolic pair (:18,:346-347),
importance-weighted loss + priority updates with the max/mean mix
eta (:364-374, buffer_set_priorities :413-416).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OnlineAndTarget, RNNOffPolicyLearnerState
from stoix_tpu.buffers import make_prioritised_trajectory_buffer
from stoix_tpu.ops import SIGNED_HYPERBOLIC_PAIR, n_step_bootstrapped_returns
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.off_policy_core import pmean_grads
from stoix_tpu.systems.runner import AnakinSetup
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.training import make_learning_rate


def get_learner_fn(env, q_network, q_update, buffer, config, cell_type, hidden_size):
    from stoix_tpu.networks.base import ScannedRNN

    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    n_step = int(config.system.get("n_step", 5))
    burn_in = int(config.system.get("burn_in_length", 8))
    train_eps = float(config.system.training_epsilon)
    priority_eta = float(config.system.get("priority_eta", 0.9))
    importance_beta = float(config.system.get("importance_sampling_exponent", 0.6))
    tx = SIGNED_HYPERBOLIC_PAIR

    def _env_step(learner_state: RNNOffPolicyLearnerState, _):
        (params, opt_states, buffer_state, key, env_state, last_timestep,
         done, truncated, hstate) = learner_state
        key, act_key = jax.random.split(key)
        # Hidden state resets on done OR truncation, matching the flags the
        # training replay uses (a mismatch desynchronizes stored hstates).
        reset_flag = jnp.logical_or(done, truncated)
        obs_t = jax.tree.map(lambda x: x[None], last_timestep.observation)
        new_hstate, dist = q_network.apply(
            params.online, hstate, (obs_t, reset_flag[None]), train_eps
        )
        action = dist.sample(seed=act_key)[0]
        env_state, timestep = env.step(env_state, action)
        next_done = timestep.discount == 0.0
        next_trunc = jnp.logical_and(timestep.last(), timestep.discount != 0.0)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "done": reset_flag,  # reset flag ENTERING the step
            "hstate": jax.tree.map(lambda x: x, hstate),  # carry at step start
            "info": timestep.extras["episode_metrics"],
        }
        new_state = RNNOffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep,
            next_done, next_trunc, new_hstate,
        )
        return new_state, data

    def _loss_fn(online_params, target_params, seq, probs):
        # seq leaves [B, L, ...]; unroll time-major [L, B, ...].
        tm = lambda x: jnp.swapaxes(x, 0, 1)
        obs = jax.tree.map(tm, seq["obs"])
        dones = tm(seq["done"])
        init_h = jax.tree.map(lambda x: x[:, 0], seq["hstate"])  # [B, H]

        # Burn-in: warm both nets' carries without gradient.
        burn_obs = jax.tree.map(lambda x: x[:burn_in], obs)
        rest_obs = jax.tree.map(lambda x: x[burn_in:], obs)
        burn_dones, rest_dones = dones[:burn_in], dones[burn_in:]
        h_online, _ = q_network.apply(online_params, init_h, (burn_obs, burn_dones), 0.0)
        h_target, _ = q_network.apply(target_params, init_h, (burn_obs, burn_dones), 0.0)
        h_online = jax.lax.stop_gradient(h_online)
        h_target = jax.lax.stop_gradient(h_target)

        _, online_dist = q_network.apply(online_params, h_online, (rest_obs, rest_dones), 0.0)
        _, target_dist = q_network.apply(target_params, h_target, (rest_obs, rest_dones), 0.0)
        q_online = online_dist.preferences  # [L', B, A]
        q_target = target_dist.preferences

        action = tm(seq["action"])[burn_in:]
        reward = tm(seq["reward"])[burn_in:]
        discount = tm(seq["discount"])[burn_in:]

        # Transformed double n-step targets (selector = online argmax).
        selector = jnp.argmax(q_online, axis=-1)
        v_raw = tx.apply_inv(
            jnp.take_along_axis(q_target, selector[..., None], axis=-1)[..., 0]
        )
        targets = n_step_bootstrapped_returns(
            reward[:-1].swapaxes(0, 1),
            (gamma * discount[:-1]).swapaxes(0, 1),
            v_raw[1:].swapaxes(0, 1),
            n=n_step,
        ).swapaxes(0, 1)
        targets = tx.apply(targets)

        qa = jnp.take_along_axis(q_online, action[..., None], axis=-1)[..., 0][:-1]
        td = jax.lax.stop_gradient(targets) - qa  # [L'-1, B]

        # Sequence priorities: eta * max|td| + (1-eta) * mean|td|.
        abs_td = jnp.abs(td)
        new_priorities = priority_eta * jnp.max(abs_td, axis=0) + (
            1.0 - priority_eta
        ) * jnp.mean(abs_td, axis=0)

        weights = (1.0 / jnp.maximum(probs, 1e-9)) ** importance_beta
        weights = weights / jnp.max(weights)
        loss = jnp.mean(weights[None, :] * 0.5 * td**2)
        return loss, (new_priorities, {"q_loss": loss, "mean_q": jnp.mean(q_online)})

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key = jax.random.split(key)
        sample = buffer.sample(buffer_state, sample_key)
        grads, (new_priorities, loss_info) = jax.grad(_loss_fn, has_aux=True)(
            params.online, params.target, sample.experience, sample.probabilities
        )
        grads = pmean_grads(grads)
        updates, opt_states = q_update(grads, opt_states)
        online = optax.apply_updates(params.online, updates)
        target = optax.incremental_update(online, params.target, tau)
        buffer_state = buffer.set_priorities(buffer_state, sample.indices, new_priorities)
        return (OnlineAndTarget(online, target), opt_states, buffer_state, key), loss_info

    def _update_step(learner_state: RNNOffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        (params, opt_states, buffer_state, key, env_state, timestep,
         done, truncated, hstate) = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        buffer_state = buffer.add(
            buffer_state, jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)
        )
        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = RNNOffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep,
            done, truncated, hstate,
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: RNNOffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


class RecurrentQNetwork:
    """pre_torso -> ScannedRNN -> epsilon-greedy Q head over sequences."""

    def __init__(self, config, num_actions, hidden_size, cell_type):
        from stoix_tpu.networks.base import RecurrentActor, ScannedRNN
        from stoix_tpu.networks.heads import DiscreteQNetworkHead

        net_cfg = config.network.actor_network
        self.module = RecurrentActor(
            action_head=DiscreteQNetworkHead(
                action_dim=num_actions,
                epsilon=float(config.system.evaluation_epsilon),
            ),
            rnn=ScannedRNN(hidden_size=hidden_size, cell_type=cell_type),
            pre_torso=config_lib.instantiate(net_cfg.pre_torso),
            post_torso=config_lib.instantiate(net_cfg.post_torso),
            input_layer=config_lib.instantiate(net_cfg.input_layer),
        )

    def init(self, key, hstate, inputs):
        return self.module.init(key, hstate, inputs)

    def apply(self, params, hstate, inputs, epsilon=0.0):
        # RecurrentActor passes head kwargs through observation mask path only;
        # epsilon is applied by rebuilding the distribution over preferences.
        hstate, dist = self.module.apply(params, hstate, inputs)
        from stoix_tpu.ops import EpsilonGreedy

        return hstate, EpsilonGreedy(dist.preferences, epsilon)


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import ScannedRNN

    config.system.action_dim = env.num_actions
    hidden_size = int(config.network.get("rnn_hidden_size", 128))
    cell_type = str(config.network.get("rnn_cell_type", "gru"))
    q_network = RecurrentQNetwork(config, env.num_actions, hidden_size, cell_type)

    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.q_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )

    key, net_key, env_key = jax.random.split(key, 3)
    dummy_obs = jax.tree.map(lambda x: x[None, None], env.observation_value())
    dummy_done = jnp.zeros((1, 1), bool)
    dummy_h = ScannedRNN.initialize_carry(cell_type, hidden_size, (1,))
    online = q_network.init(net_key, dummy_h, (dummy_obs, dummy_done))
    params = OnlineAndTarget(online, online)
    opt_state = q_optim.init(online)

    n_shards = int(mesh.shape["data"])
    update_batch = int(config.arch.get("update_batch_size", 1))
    envs_axis = int(config.arch.total_num_envs) // update_batch
    seq_len = int(config.system.get("burn_in_length", 8)) + int(
        config.system.get("train_length", 8)
    )
    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * seq_len
    )
    buffer = make_prioritised_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=seq_len,
        period=int(config.system.get("period", 4)),
        max_length_time_axis=max_length,
        priority_exponent=float(config.system.get("priority_exponent", 0.6)),
    )
    dummy_item = {
        "obs": env.observation_value(),
        "action": jnp.zeros((), jnp.int32),
        "reward": jnp.zeros((), jnp.float32),
        "discount": jnp.zeros((), jnp.float32),
        "done": jnp.zeros((), bool),
        "hstate": jax.tree.map(
            lambda x: x[0], ScannedRNN.initialize_carry(cell_type, hidden_size, (1,))
        ),
    }
    buffer_state = buffer.init(dummy_item)

    state_specs = RNNOffPolicyLearnerState(
        params=P(), opt_states=P(), buffer_state=P("data"), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
        done=P(None, "data"), truncated=P(None, "data"), hstates=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = RNNOffPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_state, update_batch),
        buffer_state=jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n_shards, update_batch) + x.shape), buffer_state
        ),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
        done=jnp.zeros((update_batch, envs_axis), bool),
        truncated=jnp.zeros((update_batch, envs_axis), bool),
        hstates=ScannedRNN.initialize_carry(cell_type, hidden_size, (update_batch, envs_axis)),
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(
        env, q_network, q_optim.update, buffer, config, cell_type, hidden_size
    )

    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    def rnn_act_fn(params, hstate, observation, done, act_key):
        obs_t = jax.tree.map(lambda x: x[None, None], observation)
        done_t = jnp.asarray(done).reshape(1, 1)
        hstate, dist = q_network.apply(
            params, hstate, (obs_t, done_t), float(config.system.evaluation_epsilon)
        )
        greedy = bool(config.arch.get("evaluation_greedy", False))
        action = dist.mode() if greedy else dist.sample(seed=act_key)
        return hstate, action[0, 0]

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=rnn_act_fn,
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.online),
    )


def run_experiment(config: Any) -> float:
    from stoix_tpu.systems.runner import run_rnn_anakin_experiment

    return run_rnn_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_rec_r2d2.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
