"""Anakin Munchausen-DQN (reference stoix/systems/q_learning/ff_mdqn.py, 574
LoC): adds a scaled log-policy bonus to the reward and a soft backup
(munchausen_q_learning, reference stoix/utils/loss.py:190)."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from stoix_tpu.base_types import Transition
from stoix_tpu.ops import losses
from stoix_tpu.systems.q_learning.q_family import run_q_experiment
from stoix_tpu.utils import config as config_lib


def mdqn_loss(online_params: Any, target_params: Any, batch: Transition, q_apply, config):
    q_tm1 = q_apply(online_params, batch.obs, 0.0).preferences
    q_t_target = q_apply(target_params, batch.next_obs, 0.0).preferences
    q_tm1_target = q_apply(target_params, batch.obs, 0.0).preferences
    d_t = float(config.system.gamma) * (1.0 - batch.done.astype(jnp.float32))
    loss = losses.munchausen_q_learning(
        q_tm1,
        batch.action,
        batch.reward,
        d_t,
        q_t_target,
        q_tm1_target,
        entropy_temperature=float(config.system.get("entropy_temperature", 0.03)),
        munchausen_coefficient=float(config.system.get("munchausen_coefficient", 0.9)),
        clip_value_min=float(config.system.get("clip_value_min", -1e3)),
    )
    return loss, {"q_loss": loss, "mean_q": jnp.mean(q_tm1)}


def run_experiment(config: Any) -> float:
    return run_q_experiment(config, mdqn_loss)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_mdqn.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
