"""Anakin C51 (reference stoix/systems/q_learning/ff_c51.py, 588 LoC):
categorical distributional Q-learning with a double-Q projection target
(categorical_double_q_learning, reference stoix/utils/loss.py:81) and the
DistributionalDiscreteQNetwork head."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from stoix_tpu.base_types import Transition
from stoix_tpu.ops import losses
from stoix_tpu.systems.q_learning.q_family import run_q_experiment
from stoix_tpu.utils import config as config_lib


def c51_loss(online_params: Any, target_params: Any, batch: Transition, q_apply, config):
    _, q_logits_tm1, q_atoms_tm1 = q_apply(online_params, batch.obs, 0.0)
    _, q_logits_t, q_atoms_t = q_apply(target_params, batch.next_obs, 0.0)
    # Double-Q: the ONLINE network selects the bootstrap action, the target
    # network evaluates it (reference ff_c51.py:164-179).
    dist_selector, _, _ = q_apply(online_params, batch.next_obs, 0.0)
    q_t_selector = dist_selector.preferences
    d_t = float(config.system.gamma) * (1.0 - batch.done.astype(jnp.float32))
    loss = losses.categorical_double_q_learning(
        q_logits_tm1, q_atoms_tm1, batch.action, batch.reward, d_t,
        q_logits_t, q_atoms_t, q_t_selector,
    )
    return loss, {"q_loss": loss}


def _head_kwargs(config: Any) -> dict:
    return dict(
        num_atoms=int(config.system.get("num_atoms", 51)),
        vmin=float(config.system.get("vmin", -10.0)),
        vmax=float(config.system.get("vmax", 10.0)),
    )


def run_experiment(config: Any) -> float:
    return run_q_experiment(config, c51_loss, head_kwargs=_head_kwargs(config))


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_c51.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
