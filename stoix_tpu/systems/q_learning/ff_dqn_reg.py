"""Anakin DQN-Reg (reference stoix/systems/q_learning/ff_dqn_reg.py, 574 LoC):
DQN with a regularization term that directly penalizes Q(s,a)
(loss = reg * Q(s,a) + 0.5 td^2 — Co-Reyes et al., Evolving RL Algorithms)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from stoix_tpu.base_types import Transition
from stoix_tpu.systems.q_learning.q_family import run_q_experiment
from stoix_tpu.utils import config as config_lib


def dqn_reg_loss(online_params: Any, target_params: Any, batch: Transition, q_apply, config):
    q_tm1 = q_apply(online_params, batch.obs, 0.0).preferences
    q_t = q_apply(target_params, batch.next_obs, 0.0).preferences
    d_t = float(config.system.gamma) * (1.0 - batch.done.astype(jnp.float32))
    qa_tm1 = jnp.take_along_axis(q_tm1, batch.action[..., None], axis=-1)[..., 0]
    target = jax.lax.stop_gradient(batch.reward + d_t * jnp.max(q_t, axis=-1))
    td = target - qa_tm1
    reg = float(config.system.get("regularizer_coeff", 0.1))
    loss = jnp.mean(reg * qa_tm1 + 0.5 * td**2)
    return loss, {"q_loss": loss, "mean_q": jnp.mean(q_tm1)}


def run_experiment(config: Any) -> float:
    return run_q_experiment(config, dqn_reg_loss)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_dqn_reg.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
