"""Continuous-action variant of ff_mpo (reference
stoix/systems/mpo/ff_mpo_continuous.py) — shares the ff_mpo learner; the
continuous head comes from the network config."""

from __future__ import annotations

from typing import Any

from stoix_tpu.systems.mpo.ff_mpo import learner_setup  # noqa: F401
from stoix_tpu.systems.runner import run_anakin_experiment
from stoix_tpu.utils import config as config_lib


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_mpo_continuous.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
