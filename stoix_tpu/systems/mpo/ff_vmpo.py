"""Anakin V-MPO (reference stoix/systems/mpo/ff_vmpo.py, 623 LoC / continuous
:698) — on-policy MPO: E-step reweights the TOP HALF of advantages through a
learnable temperature (eta) dual, M-step maximizes weighted log-likelihood
under a KL trust region enforced by a learnable alpha dual (decoupled
mean/stddev alphas for Gaussian policies, reference mpo_types.py:23-31).

The policy that ACTS is a slow-moving TARGET actor, refreshed from the online
actor every `actor_target_period` SGD steps (reference ff_vmpo.py:77 "We act
with target params in VMPO", :270-276 periodic_update). The KL trust region
is KL(target || online), so the online policy can take many small steps away
from a fixed anchor before the anchor jumps — this is what makes V-MPO's
16-epoch reuse of each rollout stable.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OnlineAndTarget, OnPolicyLearnerState
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import distributions as dists
from stoix_tpu.ops import truncated_generalized_advantage_estimation
from stoix_tpu.systems import anakin
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class VMPOParams(NamedTuple):
    actor_params: Any  # OnlineAndTarget — acting + the KL anchor use .target
    critic_params: Any
    log_temperature: jax.Array  # eta dual
    log_alpha: jax.Array  # KL dual (scalar for categorical; [2] mean/std for Gaussian)
    step_count: jax.Array  # SGD steps taken, drives the periodic target refresh


class VMPOOptStates(NamedTuple):
    actor_opt_state: Any
    critic_opt_state: Any
    dual_opt_state: Any


def _softplus(x):
    return jax.nn.softplus(x) + 1e-8


# Dual variables live in softplus space; keep them from drifting so far
# negative that softplus underflows and the dual can never recover
# (the reference projects duals the same way, continuous_loss.py).
_MIN_LOG_DUAL = -18.0


def project_duals(log_temperature, log_alpha):
    return (
        jnp.maximum(log_temperature, _MIN_LOG_DUAL),
        jnp.maximum(log_alpha, _MIN_LOG_DUAL),
    )


def gaussian_params(dist):
    """(loc, scale) of the underlying diagonal Gaussian.

    Supports both the raw MultivariateNormalDiag policy and the squashed
    Independent(TanhNormal) policy (the reference's continuous-MPO head,
    NormalAffineTanhDistributionHead — continuous_loss.py reads the pre-tanh
    Normal's mean/stddev for the decoupled KLs exactly like this)."""
    if hasattr(dist, "scale_diag"):
        return dist.loc, dist.scale_diag
    inner = getattr(dist, "distribution", dist)  # unwrap Independent
    if hasattr(inner, "base"):  # TanhNormal wraps a Normal
        return inner.base.loc, inner.base.scale
    return inner.loc, inner.scale


def gaussian_kls_per_dim(b_loc, b_scale, o_loc, o_scale):
    """Decoupled per-dimension KL(behavior || online) for diag Gaussians
    (reference continuous_loss.py per_dim_constraining): mean-KL holds the
    stddev fixed at the behavior's, stddev-KL holds the mean fixed. Returns
    (kl_mean, kl_stddev), each shaped [action_dim] (batch-averaged)."""
    kl_mean = 0.5 * jnp.square((o_loc - b_loc) / b_scale)
    kl_std = (
        jnp.log(o_scale / b_scale)
        + 0.5 * jnp.square(b_scale / o_scale)
        - 0.5
    )
    reduce_dims = tuple(range(kl_mean.ndim - 1))
    return jnp.mean(kl_mean, axis=reduce_dims), jnp.mean(kl_std, axis=reduce_dims)


def decomposed_dists(target_dist, online_dist):
    """Fixed-stddev / fixed-mean decompositions of the online Gaussian policy.

    The reference's continuous M-step (continuous_loss.py:232-252) updates the
    mean through a distribution that borrows the TARGET's stddev, and the
    stddev through one that borrows the TARGET's mean — decoupling the two
    gradient paths (Abdolmaleki et al.). Returns (fixed_stddev, fixed_mean)
    distributions matching the policy family (squashed TanhNormal or raw
    diagonal Gaussian)."""
    b_loc, b_scale = gaussian_params(target_dist)
    o_loc, o_scale = gaussian_params(online_dist)
    inner = getattr(target_dist, "distribution", target_dist)
    if hasattr(inner, "base"):  # TanhNormal: rebuild with the same affine range
        minimum = inner._shift - inner._scale
        maximum = inner._shift + inner._scale
        fixed_std = dists.Independent(dists.TanhNormal(o_loc, b_scale, minimum, maximum), 1)
        fixed_mean = dists.Independent(dists.TanhNormal(b_loc, o_scale, minimum, maximum), 1)
    else:
        fixed_std = dists.MultivariateNormalDiag(o_loc, b_scale)
        fixed_mean = dists.MultivariateNormalDiag(b_loc, o_scale)
    return fixed_std, fixed_mean


def init_log_duals(config, continuous: bool, act_dim: int):
    """(log_temperature, log_alpha) initial values shared by MPO and V-MPO.

    Continuous policies get per-dimension alpha duals [2, A]: row 0 = mean KL,
    row 1 = stddev KL (reference init_log_alpha_mean=10,
    init_log_alpha_stddev=500)."""
    default_temp = 10.0 if continuous else 3.0
    log_temperature = jnp.asarray(
        float(config.system.get("init_log_temperature", default_temp))
    )
    if continuous:
        init_mean = float(config.system.get("init_log_alpha_mean",
                                            config.system.get("init_log_alpha", 10.0)))
        init_std = float(config.system.get("init_log_alpha_stddev", 500.0))
        log_alpha = jnp.stack(
            [jnp.full((act_dim,), init_mean), jnp.full((act_dim,), init_std)]
        )
    else:
        log_alpha = jnp.asarray(float(config.system.get("init_log_alpha", 3.0)))
    return log_temperature, log_alpha


def decoupled_alpha_losses(log_alpha, kl_mean, kl_std, eps_mean, eps_std):
    """Per-dimension alpha dual losses + KL penalty for continuous policies.
    Returns (alpha_loss, kl_loss, kl_metric) — shared by MPO and V-MPO."""
    alpha_mean = _softplus(log_alpha[0])
    alpha_std = _softplus(log_alpha[1])
    alpha_loss = jnp.sum(
        alpha_mean * (eps_mean - jax.lax.stop_gradient(kl_mean))
    ) + jnp.sum(alpha_std * (eps_std - jax.lax.stop_gradient(kl_std)))
    kl_loss = jnp.sum(jax.lax.stop_gradient(alpha_mean) * kl_mean) + jnp.sum(
        jax.lax.stop_gradient(alpha_std) * kl_std
    )
    return alpha_loss, kl_loss, jnp.sum(kl_mean) + jnp.sum(kl_std)


def get_learner_fn(env, apply_fns, update_fns, config, continuous: bool):
    actor_apply, critic_apply = apply_fns
    actor_update, critic_update, dual_update = update_fns
    gamma = float(config.system.gamma)
    eps_eta = float(config.system.get("epsilon_eta", 0.5))
    eps_alpha = float(config.system.get("epsilon_alpha", 0.001))
    eps_alpha_mean = float(config.system.get("epsilon_alpha_mean", 0.0075))
    eps_alpha_stddev = float(config.system.get("epsilon_alpha_stddev", 1e-5))

    def _env_step(learner_state: OnPolicyLearnerState, _):
        params, opt_states, key, env_state, last_timestep = learner_state
        key, act_key = jax.random.split(key)
        # Act with the TARGET actor (reference ff_vmpo.py:77).
        dist = actor_apply(params.actor_params.target, last_timestep.observation)
        action = dist.sample(seed=act_key)
        env_state, timestep = env.step(env_state, action)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "truncated": jnp.logical_and(timestep.last(), timestep.discount != 0.0),
            "next_obs": timestep.extras["next_obs"],
            "info": timestep.extras["episode_metrics"],
        }
        return OnPolicyLearnerState(params, opt_states, key, env_state, timestep), data

    def _loss_fn(learnable, target_actor_params, traj, advantages):
        actor_params, log_temperature, log_alpha = learnable
        eta = _softplus(log_temperature)

        flat = tree_merge_leading_dims((traj, advantages), 2)
        traj_f, adv = flat
        dist = actor_apply(actor_params, traj_f["obs"])
        target_dist = actor_apply(target_actor_params, traj_f["obs"])
        log_prob = dist.log_prob(traj_f["action"])

        # E-step: top-half advantages only (the V-MPO distinctive).
        n = adv.shape[0]
        k = n // 2
        top_idx = jnp.argsort(-adv)[:k]
        adv_top = adv[top_idx]
        logw = adv_top / eta
        weights = jax.nn.softmax(logw)

        # Temperature dual loss (closes the E-step constraint).
        temperature_loss = eta * eps_eta + eta * (
            jax.nn.logsumexp(logw, axis=0) - jnp.log(jnp.asarray(k, jnp.float32))
        )

        # M-step: weighted max-likelihood on the selected samples. Weights are
        # E-step constants — stop_gradient keeps the policy loss from leaking
        # gradients into the temperature dual (reference continuous_loss.py:54).
        policy_loss = -jnp.sum(jax.lax.stop_gradient(weights) * log_prob[top_idx])

        # KL trust region to the slow-moving TARGET policy (reference
        # ff_vmpo.py:136-141 — kl = target.kl_divergence(online)).
        if continuous:
            o_loc, o_scale = gaussian_params(dist)
            b_loc, b_scale = gaussian_params(target_dist)
            # Decoupled per-dimension mean/stddev KLs with per-dimension
            # alpha duals [2, A] (reference continuous_loss.py,
            # per_dim_constraining=True).
            kl_mean, kl_std = gaussian_kls_per_dim(b_loc, b_scale, o_loc, o_scale)
            alpha_loss, kl_loss, kl_metric = decoupled_alpha_losses(
                log_alpha, kl_mean, kl_std, eps_alpha_mean, eps_alpha_stddev
            )
        else:
            behavior = dists.Categorical(jax.lax.stop_gradient(target_dist.logits))
            kl = jnp.mean(behavior.kl_divergence(dist))
            alpha = _softplus(log_alpha)
            alpha_loss = jnp.sum(alpha * (eps_alpha - jax.lax.stop_gradient(kl)))
            kl_loss = jnp.sum(jax.lax.stop_gradient(alpha) * kl)
            kl_metric = kl

        total = policy_loss + temperature_loss + alpha_loss + kl_loss
        metrics = {
            "policy_loss": policy_loss,
            "temperature": eta,
            "kl": kl_metric,
        }
        return total, metrics

    def _update_epoch(carry, _):
        # One full-batch pass over the rollout. Multiple epochs re-use the
        # trajectory (reference ff_vmpo epochs=16); the KL trust region is
        # anchored at the slow-moving target actor (refreshed every
        # actor_target_period SGD steps below), and advantages are recomputed
        # as the critic improves.
        params, opt_states, traj = carry

        v_tm1 = critic_apply(params.critic_params, traj["obs"])
        v_t = critic_apply(params.critic_params, traj["next_obs"])
        advantages, targets = truncated_generalized_advantage_estimation(
            traj["reward"],
            gamma * traj["discount"],
            float(config.system.get("gae_lambda", 0.95)),
            v_tm1=v_tm1,
            v_t=v_t,
            truncation_t=traj["truncated"].astype(jnp.float32),
        )

        learnable = (params.actor_params.online, params.log_temperature, params.log_alpha)
        grads, metrics = jax.grad(_loss_fn, has_aux=True)(
            learnable, params.actor_params.target, traj, advantages
        )

        def critic_loss_fn(critic_params):
            v = critic_apply(critic_params, traj["obs"])
            loss = 0.5 * jnp.mean((v - jax.lax.stop_gradient(targets)) ** 2)
            return loss, {"value_loss": loss}

        critic_grads, critic_metrics = jax.grad(critic_loss_fn, has_aux=True)(
            params.critic_params
        )
        grads, critic_grads = jax.lax.pmean(
            jax.lax.pmean((grads, critic_grads), axis_name="batch"), axis_name="data"
        )
        actor_grads, temp_grads, alpha_grads = grads

        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        actor_online = optax.apply_updates(params.actor_params.online, a_updates)
        c_updates, c_opt = critic_update(critic_grads, opt_states.critic_opt_state)
        critic_params = optax.apply_updates(params.critic_params, c_updates)
        d_updates, d_opt = dual_update(
            (temp_grads, alpha_grads), opt_states.dual_opt_state
        )
        log_temperature, log_alpha = optax.apply_updates(
            (params.log_temperature, params.log_alpha), d_updates
        )
        log_temperature, log_alpha = project_duals(log_temperature, log_alpha)

        # Refresh the acting/KL-anchor target every actor_target_period SGD
        # steps (reference ff_vmpo.py:270-276 optax.periodic_update).
        step_count = params.step_count + 1
        actor_target = optax.periodic_update(
            actor_online, params.actor_params.target, step_count,
            int(config.system.get("actor_target_period", 50)),
        )

        params = VMPOParams(
            OnlineAndTarget(actor_online, actor_target), critic_params,
            log_temperature, log_alpha, step_count,
        )
        opt_states = VMPOOptStates(a_opt, c_opt, d_opt)
        return (params, opt_states, traj), {**metrics, **critic_metrics}

    def _update_step(learner_state: OnPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep = learner_state

        (params, opt_states, _), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, traj), None,
            int(config.system.get("epochs", 1)),
        )
        loss_info = jax.tree.map(lambda x: x[-1], loss_info)

        learner_state = OnPolicyLearnerState(
            params, opt_states, key, env_state, last_timestep,
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OnPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    continuous = hasattr(env.action_space(), "low")
    net_cfg = config.network
    actor_network = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    critic_network = FeedForwardCritic(
        critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
        torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
    )

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config), eps=1e-5),
    )
    critic_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.critic_lr), config), eps=1e-5),
    )
    dual_optim = optax.adam(float(config.system.get("dual_lr", 1e-2)))

    key, actor_key, critic_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_params = actor_network.init(actor_key, dummy_obs)
    critic_params = critic_network.init(critic_key, dummy_obs)
    log_temperature, log_alpha = init_log_duals(config, continuous, int(env.num_actions))
    params = VMPOParams(
        OnlineAndTarget(actor_params, actor_params), critic_params,
        log_temperature, log_alpha, jnp.zeros((), jnp.int32),
    )
    opt_states = VMPOOptStates(
        actor_optim.init(actor_params),
        critic_optim.init(critic_params),
        dual_optim.init((log_temperature, log_alpha)),
    )

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = OnPolicyLearnerState(
        params=P(), opt_states=P(), key=P("data"),
        env_state=P(None, "data"), timestep=P(None, "data"),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = OnPolicyLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_states, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)

    learn_per_shard = get_learner_fn(
        env, (actor_network.apply, critic_network.apply),
        (actor_optim.update, critic_optim.update, dual_optim.update), config, continuous,
    )
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor_network.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params.online),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_vmpo.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
