"""Anakin MPO (reference stoix/systems/mpo/ff_mpo.py, 774 LoC / continuous
ff_mpo_continuous.py, 805 LoC).

Maximum a Posteriori Policy Optimization (Abdolmaleki et al. 2018):
  - trajectory replay buffer of sequences (reference ff_mpo.py:539)
  - Q-critic trained with Retrace targets (reference multistep.py:270)
  - E-step: nonparametric improved policy via temperature-weighted Q values
    (sampled actions for continuous; all actions for discrete), with a
    learnable temperature dual
  - M-step: weighted max-likelihood under decoupled KL trust regions with
    learnable alpha duals (reference mpo_types.py:23-31, continuous_loss.py)
  - target actor/critic networks, periodic/polyak updates.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput, OffPolicyLearnerState, OnlineAndTarget
from stoix_tpu.buffers import make_trajectory_buffer
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.ops import distributions as dists
from stoix_tpu.ops import retrace_continuous
from stoix_tpu.systems import anakin, off_policy_core as core
from stoix_tpu.systems.mpo.ff_vmpo import (
    decomposed_dists,
    decoupled_alpha_losses,
    gaussian_kls_per_dim,
    gaussian_params,
    init_log_duals,
    project_duals,
)
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import tree_merge_leading_dims
from stoix_tpu.utils.training import make_learning_rate


class MPOParams(NamedTuple):
    actor_params: OnlineAndTarget
    q_params: OnlineAndTarget
    log_temperature: jax.Array
    log_alpha: jax.Array  # scalar (discrete) or [2] mean/std (continuous)


class MPOOptStates(NamedTuple):
    actor_opt_state: Any
    q_opt_state: Any
    dual_opt_state: Any


def _softplus(x):
    return jax.nn.softplus(x) + 1e-8


def get_learner_fn(env, networks, update_fns, buffer, config, continuous: bool):
    actor, q_network = networks
    actor_update, q_update, dual_update = update_fns
    gamma = float(config.system.gamma)
    tau = float(config.system.tau)
    num_samples = int(config.system.get("num_samples", 16))
    eps_eta = float(config.system.get("epsilon_eta", 0.1))
    eps_alpha = float(config.system.get("epsilon_alpha", 0.01))
    eps_alpha_mean = float(config.system.get("epsilon_alpha_mean", 0.0075))
    eps_alpha_stddev = float(config.system.get("epsilon_alpha_stddev", 1e-5))

    def _env_step(learner_state: OffPolicyLearnerState, _):
        params, opt_states, buffer_state, key, env_state, last_timestep = learner_state
        key, act_key = jax.random.split(key)
        dist = actor.apply(params.actor_params.online, last_timestep.observation)
        action = dist.sample(seed=act_key)
        log_prob = dist.log_prob(action)
        env_state, timestep = env.step(env_state, action)
        data = {
            "obs": last_timestep.observation,
            "action": action,
            "log_prob": log_prob,
            "reward": timestep.reward,
            "discount": timestep.discount,
            "info": timestep.extras["episode_metrics"],
        }
        return (
            OffPolicyLearnerState(params, opt_states, buffer_state, key, env_state, timestep),
            data,
        )

    def _q_value(q_params, obs, action):
        if continuous:
            return q_network.apply(q_params, obs, action)
        q_all = q_network.apply(q_params, obs, 0.0).preferences
        return jnp.take_along_axis(q_all, action[..., None], axis=-1)[..., 0]

    def _critic_loss_fn(q_online, params: MPOParams, seq, key):
        # Retrace targets over the sampled sequences [B, L].
        obs = seq["obs"]
        target_dist = actor.apply(params.actor_params.target, obs)
        online_log_prob = target_dist.log_prob(seq["action"])
        log_rhos = online_log_prob - seq["log_prob"]

        # v_t: expected Q under the target policy at each state.
        if continuous:
            sample_keys = jax.random.split(key, num_samples)
            sampled = jax.vmap(lambda k: target_dist.sample(seed=k))(sample_keys)  # [N,B,L,A]
            q_sampled = jax.vmap(
                lambda a: _q_value(params.q_params.target, obs, a)
            )(sampled)  # [N,B,L]
            v_t = jnp.mean(q_sampled, axis=0)
        else:
            q_all = q_network.apply(params.q_params.target, obs, 0.0).preferences
            probs = dists.Categorical(target_dist.logits).probs
            v_t = jnp.sum(probs * q_all, axis=-1)

        q_tm1 = _q_value(q_online, obs, seq["action"])  # [B, L]
        q_t_target = _q_value(params.q_params.target, obs, seq["action"])

        errors = retrace_continuous(
            q_tm1[:, :-1],
            q_t_target[:, 1:-1],
            v_t[:, 1:],
            seq["reward"][:, :-1],
            gamma * seq["discount"][:, :-1],
            log_rhos[:, 1:-1],
            float(config.system.get("retrace_lambda", 0.95)),
        )
        loss = 0.5 * jnp.mean(errors**2)
        return loss, {"q_loss": loss, "mean_q": jnp.mean(q_tm1)}

    def _policy_loss_fn(learnable, params: MPOParams, seq, key):
        actor_online, log_temperature, log_alpha = learnable
        eta = _softplus(log_temperature)
        obs = jax.tree.map(lambda x: tree_merge_leading_dims(x, 2), seq["obs"])

        target_dist = actor.apply(params.actor_params.target, obs)
        online_dist = actor.apply(actor_online, obs)

        if continuous:
            sample_keys = jax.random.split(key, num_samples)
            actions = jax.vmap(lambda k: target_dist.sample(seed=k))(sample_keys)  # [N,B,A]
            q_vals = jax.vmap(lambda a: _q_value(params.q_params.target, obs, a))(actions)
            weights = jax.nn.softmax(q_vals / eta, axis=0)  # over samples
            temperature_loss = eta * eps_eta + eta * jnp.mean(
                jax.nn.logsumexp(q_vals / eta, axis=0) - jnp.log(float(num_samples))
            )
            # Decomposed M-step (reference continuous_loss.py:232-256): the
            # mean learns through a distribution borrowing the TARGET's
            # stddev, the stddev through one borrowing the TARGET's mean —
            # two cross-entropy losses instead of one.
            fixed_std, fixed_mean = decomposed_dists(target_dist, online_dist)
            lp_mean = jax.vmap(fixed_std.log_prob)(actions)  # [N,B]
            lp_std = jax.vmap(fixed_mean.log_prob)(actions)  # [N,B]
            w = jax.lax.stop_gradient(weights)
            policy_loss = -jnp.mean(jnp.sum(w * lp_mean, axis=0)) - jnp.mean(
                jnp.sum(w * lp_std, axis=0)
            )

            b_loc, b_scale = gaussian_params(target_dist)
            o_loc, o_scale = gaussian_params(online_dist)
            # Decoupled per-dimension mean/stddev KLs with per-dimension
            # alpha duals [2, A] (reference continuous_loss.py,
            # per_dim_constraining=True).
            kl_mean, kl_std = gaussian_kls_per_dim(b_loc, b_scale, o_loc, o_scale)
            alpha_loss, kl_loss, kl_metric = decoupled_alpha_losses(
                log_alpha, kl_mean, kl_std, eps_alpha_mean, eps_alpha_stddev
            )
        else:
            q_all = q_network.apply(params.q_params.target, obs, 0.0).preferences  # [B, A]
            prior_logits = dists.Categorical(target_dist.logits).logits
            # Nonparametric posterior weighted by the prior, in log space
            # (prior*exp(q/eta) overflows fp32 once eta shrinks below ~1).
            improved = jax.nn.softmax(q_all / eta + prior_logits, axis=-1)
            temperature_loss = eta * eps_eta + eta * jnp.mean(
                jax.nn.logsumexp(q_all / eta + prior_logits, axis=-1)
            )
            log_probs_all = online_dist.logits
            policy_loss = -jnp.mean(
                jnp.sum(jax.lax.stop_gradient(improved) * log_probs_all, axis=-1)
            )
            kl = jnp.mean(
                dists.Categorical(target_dist.logits).kl_divergence(online_dist)
            )
            alpha = _softplus(log_alpha)
            alpha_loss = jnp.sum(alpha * (eps_alpha - jax.lax.stop_gradient(kl)))
            kl_loss = jnp.sum(jax.lax.stop_gradient(alpha) * kl)
            kl_metric = kl

        total = policy_loss + temperature_loss + alpha_loss + kl_loss
        return total, {"policy_loss": policy_loss, "temperature": eta, "kl": kl_metric}

    def _update_epoch(carry, _):
        params, opt_states, buffer_state, key = carry
        key, sample_key, critic_key, policy_key = jax.random.split(key, 4)
        seq = buffer.sample(buffer_state, sample_key).experience  # [B, L, ...]

        q_grads, q_metrics = jax.grad(_critic_loss_fn, has_aux=True)(
            params.q_params.online, params, seq, critic_key
        )
        learnable = (params.actor_params.online, params.log_temperature, params.log_alpha)
        p_grads, p_metrics = jax.grad(_policy_loss_fn, has_aux=True)(
            learnable, params, seq, policy_key
        )
        q_grads, p_grads = jax.lax.pmean(
            jax.lax.pmean((q_grads, p_grads), axis_name="batch"), axis_name="data"
        )
        actor_grads, temp_grads, alpha_grads = p_grads

        q_updates, q_opt = q_update(q_grads, opt_states.q_opt_state)
        q_online = optax.apply_updates(params.q_params.online, q_updates)
        q_target = optax.incremental_update(q_online, params.q_params.target, tau)

        a_updates, a_opt = actor_update(actor_grads, opt_states.actor_opt_state)
        actor_online = optax.apply_updates(params.actor_params.online, a_updates)
        actor_target = optax.incremental_update(
            actor_online, params.actor_params.target, tau
        )

        d_updates, d_opt = dual_update(
            (temp_grads, alpha_grads), opt_states.dual_opt_state
        )
        log_temperature, log_alpha = optax.apply_updates(
            (params.log_temperature, params.log_alpha), d_updates
        )
        log_temperature, log_alpha = project_duals(log_temperature, log_alpha)

        params = MPOParams(
            OnlineAndTarget(actor_online, actor_target),
            OnlineAndTarget(q_online, q_target),
            log_temperature,
            log_alpha,
        )
        return (params, MPOOptStates(a_opt, q_opt, d_opt), buffer_state, key), {
            **q_metrics, **p_metrics,
        }

    def _update_step(learner_state: OffPolicyLearnerState, _):
        learner_state, traj = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, buffer_state, key, env_state, timestep = learner_state
        store = {k: v for k, v in traj.items() if k != "info"}
        batch = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), store)  # [E, T, ...]
        buffer_state = buffer.add(buffer_state, batch)

        (params, opt_states, buffer_state, key), loss_info = jax.lax.scan(
            _update_epoch, (params, opt_states, buffer_state, key), None,
            int(config.system.epochs),
        )
        learner_state = OffPolicyLearnerState(
            params, opt_states, buffer_state, key, env_state, timestep
        )
        return learner_state, (traj["info"], loss_info)

    def learner_fn(learner_state: OffPolicyLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        state, (episode_info, loss_info) = jax.lax.scan(
            jax.vmap(_update_step, axis_name="batch"),
            state, None, int(config.arch.num_updates_per_eval),
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(state, episode_info, loss_info)

    return learner_fn


def learner_setup(env: envs.Environment, config: Any, mesh: Mesh, key: jax.Array) -> AnakinSetup:
    from stoix_tpu.networks.base import FeedForwardActor, FeedForwardCritic

    config.system.action_dim = env.num_actions
    continuous = hasattr(env.action_space(), "low")
    net_cfg = config.network

    actor = FeedForwardActor(
        action_head=config_lib.instantiate(
            net_cfg.actor_network.action_head,
            **anakin.head_kwargs_for_env(net_cfg.actor_network.action_head, env),
        ),
        torso=config_lib.instantiate(net_cfg.actor_network.pre_torso),
        input_layer=config_lib.instantiate(net_cfg.actor_network.input_layer),
    )
    if continuous:
        q_network = FeedForwardCritic(
            critic_head=config_lib.instantiate(net_cfg.critic_network.critic_head),
            torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
            input_layer=config_lib.instantiate(net_cfg.critic_network.input_layer),
        )
    else:
        from stoix_tpu.networks.heads import DiscreteQNetworkHead

        q_network = FeedForwardActor(
            action_head=DiscreteQNetworkHead(action_dim=env.num_actions, epsilon=0.0),
            torso=config_lib.instantiate(net_cfg.critic_network.pre_torso),
            input_layer=config_lib.instantiate(
                net_cfg.actor_network.input_layer
            ),
        )

    actor_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.actor_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    q_optim = optax.chain(
        optax.clip_by_global_norm(float(config.system.max_grad_norm)),
        optax.adam(make_learning_rate(float(config.system.q_lr), config,
                                      int(config.system.epochs)), eps=1e-5),
    )
    dual_optim = optax.adam(float(config.system.get("dual_lr", 1e-2)))

    key, actor_key, q_key, env_key = jax.random.split(key, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    actor_p = actor.init(actor_key, dummy_obs)
    if continuous:
        dummy_act = jnp.asarray(env.action_value(), jnp.float32)[None]
        q_p = q_network.init(q_key, dummy_obs, dummy_act)
    else:
        q_p = q_network.init(q_key, dummy_obs)
    log_temperature, log_alpha = init_log_duals(config, continuous, int(env.num_actions))
    params = MPOParams(
        OnlineAndTarget(actor_p, actor_p), OnlineAndTarget(q_p, q_p),
        log_temperature, log_alpha,
    )
    opt_states = MPOOptStates(
        actor_optim.init(actor_p), q_optim.init(q_p),
        dual_optim.init((log_temperature, log_alpha)),
    )

    local_envs, sample_batch, max_length = core.trajectory_buffer_sizing(
        config, mesh, 2 * int(config.system.rollout_length)
    )
    buffer = make_trajectory_buffer(
        add_batch_size=local_envs,
        sample_batch_size=sample_batch,
        sample_sequence_length=int(config.system.get("sample_sequence_length", 8)),
        period=int(config.system.get("sample_period", 1)),
        max_length_time_axis=max_length,
    )
    dummy_item = {
        "obs": env.observation_value(),
        "action": jnp.asarray(
            env.action_value(), jnp.float32 if continuous else jnp.int32
        ),
        "log_prob": jnp.zeros((), jnp.float32),
        "reward": jnp.zeros((), jnp.float32),
        "discount": jnp.zeros((), jnp.float32),
    }
    buffer_state = buffer.init(dummy_item)

    learn_per_shard = get_learner_fn(
        env, (actor, q_network),
        (actor_optim.update, q_optim.update, dual_optim.update),
        buffer, config, continuous,
    )
    learner_state, state_specs = core.assemble_off_policy_state(
        config, mesh, env, params, opt_states, buffer_state, key, env_key
    )

    learn = core.wrap_learn(learn_per_shard, mesh, state_specs)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, actor.apply),
        eval_params_fn=lambda s: anakin.unbatch_params(s.params.actor_params.online),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(), "default/anakin/default_ff_mpo.yaml", sys.argv[1:]
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
