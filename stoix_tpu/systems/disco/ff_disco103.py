"""Anakin Disco-RL (disco103) — an agent trained by a meta update rule.

Behavioral parity: reference stoix/systems/disco_rl/anakin/ff_disco103.py
(659 LoC): rollout -> epoch/env-minibatch scans where the per-step loss comes
from a DiscoUpdateRule (meta-network) instead of a hand-written objective;
the rule carries an evolving meta-state (EMA target params); meta-params are
fixed (pretrained) and never trained.

TPU-native redesign: same global-mesh shard_map skeleton as ff_ppo (see
systems/ppo/anakin/ff_ppo.py header); minibatches are over ENVS, keeping the
time axis contiguous for the rule's trajectory processing (the reference
permutes axis=1 identically, ff_disco103.py:215-228). The unavailable
external disco_rl package is replaced by the first-party rule in
stoix_tpu/systems/disco/update_rule.py — see its docstring for the
pretrained-weights gap and the grounded mode that learns without them.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from stoix_tpu import envs
from stoix_tpu.base_types import ExperimentOutput
from stoix_tpu.evaluator import get_distribution_act_fn
from stoix_tpu.networks.disco import DiscoAgentOutput
from stoix_tpu.observability import get_logger
from stoix_tpu.ops import distributions as dists
from stoix_tpu.parallel import is_coordinator
from stoix_tpu.systems.disco.update_rule import (
    DiscoUpdateRule,
    MetaState,
    UpdateRuleInputs,
    load_meta_params,
)
from stoix_tpu.systems.runner import AnakinSetup, run_anakin_experiment
from stoix_tpu.utils import config as config_lib
from stoix_tpu.utils.jax_utils import count_parameters
from stoix_tpu.utils.training import make_learning_rate


class DiscoTransition(NamedTuple):
    done: jax.Array
    truncated: jax.Array
    action: jax.Array
    reward: jax.Array
    obs: Any
    info: Any
    agent_out: DiscoAgentOutput


class DiscoLearnerState(NamedTuple):
    params: Any
    opt_states: Any
    key: jax.Array
    env_state: Any
    timestep: Any
    meta_state: MetaState


def _batched_apply(apply_fn: Callable, params: Any, observations: Any) -> DiscoAgentOutput:
    """Apply the agent over [T, E, ...] observations in one flattened call
    (bigger MXU batches than a per-step vmap; identical math)."""
    shape = jax.tree.leaves(observations)[0].shape[:2]
    flat = jax.tree.map(lambda x: x.reshape((shape[0] * shape[1],) + x.shape[2:]), observations)
    out = apply_fn(params, flat)
    return jax.tree.map(lambda x: x.reshape(shape + x.shape[1:]), out)


def get_learner_fn(
    env: envs.Environment,
    apply_fn: Callable,
    update_fn: optax.TransformUpdateFn,
    rule: DiscoUpdateRule,
    meta_params: Any,
    config: Any,
) -> Callable[[DiscoLearnerState], ExperimentOutput]:
    """Build the per-shard learner (wrapped in shard_map by setup)."""

    hyperparams = dict(config.system.get("disco_hyperparams", {}) or {})
    hyperparams.setdefault("gamma", float(config.system.gamma))
    reward_scale = float(config.system.get("reward_scale", 1.0))

    def agent_unroll_fn(params, unused_state, observations, unused_mask):
        out = _batched_apply(apply_fn, params, observations)
        return out._asdict(), unused_state

    def _env_step(learner_state: DiscoLearnerState, _: Any):
        params, opt_states, key, env_state, last_timestep, meta_state = learner_state
        key, policy_key = jax.random.split(key)

        agent_out = apply_fn(params, last_timestep.observation)
        pi = dists.Categorical(logits=agent_out.logits)
        action = pi.sample(seed=policy_key)

        env_state, timestep = env.step(env_state, action)
        done = timestep.discount == 0.0
        truncated = jnp.logical_and(timestep.last(), timestep.discount != 0.0)
        transition = DiscoTransition(
            done=done,
            truncated=truncated,
            action=action,
            reward=timestep.reward,
            obs=last_timestep.observation,
            info=timestep.extras["episode_metrics"],
            agent_out=agent_out,
        )
        return (
            DiscoLearnerState(params, opt_states, key, env_state, timestep, meta_state),
            transition,
        )

    def _loss_fn(params, minibatch: DiscoTransition, meta_state, key):
        current_out = _batched_apply(apply_fn, params, minibatch.obs)
        inputs = UpdateRuleInputs(
            observations=minibatch.obs,
            actions=minibatch.action,
            rewards=minibatch.reward[:-1] * reward_scale,
            is_terminal=minibatch.done[:-1],
            agent_out=current_out,
            behaviour_agent_out=minibatch.agent_out,
        )
        loss_per_step, new_meta_state, logs = rule(
            meta_params, params, None, inputs, hyperparams, meta_state,
            agent_unroll_fn, key,
        )
        return jnp.mean(loss_per_step), (new_meta_state, logs)

    def _update_minibatch(train_state: Tuple, minibatch: DiscoTransition):
        params, opt_states, meta_state, key = train_state
        key, loss_key = jax.random.split(key)

        grads, (meta_state, logs) = jax.grad(_loss_fn, has_aux=True)(
            params, minibatch, meta_state, loss_key
        )
        grads = jax.lax.pmean(jax.lax.pmean(grads, "batch"), "data")
        updates, opt_states = update_fn(grads, opt_states)
        params = optax.apply_updates(params, updates)
        return (params, opt_states, meta_state, key), logs

    def _update_epoch(update_state: Tuple, _: Any):
        params, opt_states, traj_batch, meta_state, key = update_state
        key, shuffle_key = jax.random.split(key)

        # Minibatch over ENVS (axis=1), keeping the time axis contiguous for
        # the trajectory-consuming rule (reference ff_disco103.py:215-228).
        num_envs = traj_batch.action.shape[1]
        permutation = jax.random.permutation(shuffle_key, num_envs)
        shuffled = jax.tree.map(lambda x: jnp.take(x, permutation, axis=1), traj_batch)
        minibatches = jax.tree.map(
            lambda x: jnp.swapaxes(
                x.reshape((x.shape[0], int(config.system.num_minibatches), -1) + x.shape[2:]),
                0,
                1,
            ),
            shuffled,
        )
        (params, opt_states, meta_state, key), logs = jax.lax.scan(
            _update_minibatch, (params, opt_states, meta_state, key), minibatches
        )
        return (params, opt_states, traj_batch, meta_state, key), logs

    def _update_step(learner_state: DiscoLearnerState, _: Any):
        learner_state, traj_batch = jax.lax.scan(
            _env_step, learner_state, None, int(config.system.rollout_length)
        )
        params, opt_states, key, env_state, last_timestep, meta_state = learner_state

        update_state = (params, opt_states, traj_batch, meta_state, key)
        update_state, loss_info = jax.lax.scan(
            _update_epoch, update_state, None, int(config.system.epochs)
        )
        params, opt_states, _, meta_state, key = update_state
        learner_state = DiscoLearnerState(
            params, opt_states, key, env_state, last_timestep, meta_state
        )
        return learner_state, (traj_batch.info, loss_info)

    def learner_fn(learner_state: DiscoLearnerState) -> ExperimentOutput:
        key = learner_state.key[0]
        state = learner_state._replace(key=key)
        batched_update_step = jax.vmap(_update_step, axis_name="batch")
        state, (episode_info, loss_info) = jax.lax.scan(
            batched_update_step, state, None, int(config.arch.num_updates_per_eval)
        )
        state = state._replace(key=state.key[None])
        loss_info = jax.lax.pmean(loss_info, axis_name="data")
        return ExperimentOutput(
            learner_state=state,
            episode_metrics=episode_info,
            train_metrics=loss_info,
        )

    return learner_fn


def learner_setup(
    env: envs.Environment, config: Any, mesh: Mesh, keys: jax.Array
) -> AnakinSetup:
    from stoix_tpu.networks.disco import DiscoAgentNetwork
    from stoix_tpu.systems import anakin

    num_actions = env.num_actions
    config.system.action_dim = num_actions
    num_bins = int(config.system.get("num_bins", 51))

    envs_per_shard = int(config.arch.total_num_envs) // int(mesh.shape["data"])
    if envs_per_shard % int(config.system.num_minibatches) != 0:
        raise ValueError(
            f"disco minibatches are over envs: arch.total_num_envs/shards "
            f"({envs_per_shard}) must be divisible by system.num_minibatches "
            f"({config.system.num_minibatches})"
        )

    rule = DiscoUpdateRule(
        num_actions=num_actions,
        num_bins=num_bins,
        vmax=float(config.system.get("vmax", 500.0)),
        mode=str(config.system.get("rule_mode", "grounded")),
        target_ema=float(config.system.get("target_ema", 0.99)),
        policy_temperature=float(config.system.get("policy_temperature", 0.5)),
    )

    net_cfg = config.network.agent_network
    network = DiscoAgentNetwork(
        shared_torso=config_lib.instantiate(net_cfg.shared_torso),
        action_conditional_torso=config_lib.instantiate(
            net_cfg.action_conditional_torso, num_actions=num_actions
        ),
        logits_head=config_lib.instantiate(net_cfg.logits_head, output_dim=num_actions),
        q_head=config_lib.instantiate(net_cfg.q_head, output_dim=num_bins),
        y_head=config_lib.instantiate(net_cfg.y_head, output_dim=num_bins),
        z_head=config_lib.instantiate(net_cfg.z_head, output_dim=num_bins),
        aux_pi_head=config_lib.instantiate(net_cfg.aux_pi_head, output_dim=num_actions),
    )

    lr = make_learning_rate(
        float(config.system.lr), config, int(config.system.epochs),
        int(config.system.num_minibatches),
    )
    optim = optax.chain(
        optax.clip(float(config.system.get("max_abs_update", 1.0))),
        optax.adam(lr, eps=1e-5),
    )

    key, net_key, meta_key, env_key = jax.random.split(keys, 4)
    dummy_obs = jax.tree.map(lambda x: x[None], env.observation_value())
    params = network.init(net_key, dummy_obs)
    opt_state = optim.init(params)

    # Pretrained meta-parameters (download seam; random fallback documented).
    meta_params, pretrained = load_meta_params(
        rule, meta_key, local_path=config.system.get("meta_params_path")
    )
    if rule.mode == "meta" and not pretrained and is_coordinator():
        get_logger("stoix_tpu.disco").warning(
            "[disco] WARNING: meta mode with random meta-params — machinery "
            "runs but targets are uninformative"
        )

    learn_per_shard = get_learner_fn(
        env, network.apply, optim.update, rule, meta_params, config
    )

    update_batch = int(config.arch.get("update_batch_size", 1))
    state_specs = DiscoLearnerState(
        params=P(),
        opt_states=P(),
        key=P("data"),
        env_state=P(None, "data"),
        timestep=P(None, "data"),
        meta_state=P(),
    )
    env_state, timestep = anakin.reset_envs_for_anakin(env, config, env_key)
    learner_state = DiscoLearnerState(
        params=anakin.broadcast_to_update_batch(params, update_batch),
        opt_states=anakin.broadcast_to_update_batch(opt_state, update_batch),
        key=anakin.make_step_keys(key, mesh, config),
        env_state=env_state,
        timestep=timestep,
        meta_state=anakin.broadcast_to_update_batch(
            rule.init_meta_state(meta_key, params), update_batch
        ),
    )
    learner_state = anakin.place_learner_state(learner_state, mesh, state_specs)
    learn = anakin.shardmap_learner(learn_per_shard, mesh, state_specs)

    if is_coordinator():
        get_logger("stoix_tpu.setup").info(
            "[setup] %s parameters | mesh %s | %s global envs",
            f"{count_parameters(params):,}", dict(mesh.shape),
            config.arch.total_num_envs,
        )

    def eval_apply(params, observation):
        return dists.Categorical(logits=network.apply(params, observation).logits)

    return AnakinSetup(
        learn=learn,
        learner_state=learner_state,
        eval_act_fn=get_distribution_act_fn(config, eval_apply),
        eval_params_fn=lambda s: jax.tree.map(lambda x: x[0], s.params),
    )


def run_experiment(config: Any) -> float:
    return run_anakin_experiment(config, learner_setup)


def main() -> float:
    import sys

    config = config_lib.compose(
        config_lib.default_config_dir(),
        "default/anakin/default_ff_disco103.yaml",
        sys.argv[1:],
    )
    return run_experiment(config)


if __name__ == "__main__":
    main()
