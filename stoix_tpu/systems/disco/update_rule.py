"""Disco update rule: a meta-network that maps trajectories of agent
predictions to per-step losses, plus the pretrained-weights seam.

Parity target: the reference drives its disco system through the external
`disco_rl` package (reference stoix/systems/disco_rl/anakin/ff_disco103.py:
39-145 uses disco_rl.update_rules.disco.DiscoUpdateRule with the published
disco_103.npz meta-parameters downloaded at setup,
ff_disco103.py:325-341). That package and its weight file are not available
in this environment (zero egress), so this module provides:

  * `DiscoUpdateRule` — the same call surface (init_params /
    init_meta_state / model_output_spec / __call__ returning per-step losses
    and an evolving meta-state holding EMA target params), with TWO modes:
      - mode="meta": a backward-LSTM meta-network over the trajectory emits
        target distributions for every agent head; the agent loss is the KL
        against them. With *pretrained* meta-params this is the DiscoRL
        discovered-algorithm path; with random init it exercises the full
        machinery (shapes/grads/meta-state) but does not teach the agent.
      - mode="grounded" (default): the targets are computed from grounded RL
        quantities in the same output space — two-hot n-step categorical
        value targets from the EMA target network, an MPO/Muesli-style
        policy-improvement target, and EMA self-consistency targets for the
        auxiliary heads. This gives a LEARNING system today and pins the
        interface the meta path shares.
  * `load_meta_params` — the download seam for the published weights
    (disco_103.npz), matching the reference's get_or_create_file flow; when
    the file is unreachable it falls back to random init with a warning.
    DOCUMENTED GAP: without the published weights the "meta" mode cannot
    reproduce the Disco103 results, only the grounded mode learns.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from stoix_tpu.networks.disco import DiscoAgentOutput
from stoix_tpu.observability import get_logger
from stoix_tpu.ops import categorical_l2_project

DISCO103_URL = (
    "https://raw.githubusercontent.com/google-deepmind/disco_rl/main/"
    "disco_rl/update_rules/weights/disco_103.npz"
)


class UpdateRuleInputs(NamedTuple):
    """One minibatch of trajectory data, time-major [T, E, ...]
    (reference disco_rl.types.UpdateRuleInputs)."""

    observations: Any
    actions: jax.Array  # [T, E]
    rewards: jax.Array  # [T-1, E]
    is_terminal: jax.Array  # [T-1, E]
    agent_out: DiscoAgentOutput  # current params outputs, [T, E, ...]
    behaviour_agent_out: DiscoAgentOutput  # rollout-time outputs


class MetaState(NamedTuple):
    target_params: Any  # EMA of agent params (the bootstrap source)
    num_updates: jax.Array


class _MetaNetwork(nn.Module):
    """Backward LSTM over the trajectory emitting per-head target logits.

    The backward direction is what lets a learned rule implement
    bootstrapping-like credit assignment: information flows from later steps
    to earlier ones, as in the published DiscoRL architecture family.
    """

    num_actions: int
    num_bins: int
    hidden_size: int = 128

    @nn.compact
    def __call__(self, feats: jax.Array) -> Dict[str, jax.Array]:
        # feats: [T, E, F] -> scan the LSTM backward over T (nn.scan keeps the
        # cell's params outside the scan body; a raw lax.scan leaks tracers).
        T, E, _ = feats.shape
        scan_cell = nn.scan(
            nn.LSTMCell,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )(features=self.hidden_size, name="meta_lstm")
        carry = nn.LSTMCell(features=self.hidden_size, parent=None).initialize_carry(
            jax.random.PRNGKey(0), feats[0].shape
        )
        _, hidden = scan_cell(carry, jnp.flip(feats, axis=0))
        hidden = jnp.flip(hidden, axis=0)  # [T, E, H]

        A, B = self.num_actions, self.num_bins
        return {
            "pi": nn.Dense(A)(hidden),
            "q": nn.Dense(A * B)(hidden).reshape(T, E, A, B),
            "y": nn.Dense(B)(hidden),
            "z": nn.Dense(A * B)(hidden).reshape(T, E, A, B),
            "aux_pi": nn.Dense(A * A)(hidden).reshape(T, E, A, A),
        }


def _kl(target_logits: jax.Array, pred_logits: jax.Array) -> jax.Array:
    """KL(softmax(target) || softmax(pred)) over the last axis."""
    t = jax.nn.log_softmax(target_logits)
    p = jax.nn.log_softmax(pred_logits)
    return jnp.sum(jnp.exp(t) * (t - p), axis=-1)


class DiscoUpdateRule:
    """First-party stand-in for disco_rl.update_rules.disco.DiscoUpdateRule."""

    def __init__(
        self,
        num_actions: int,
        num_bins: int = 51,
        vmax: float = 500.0,
        mode: str = "grounded",
        meta_hidden_size: int = 128,
        target_ema: float = 0.99,
        policy_temperature: float = 0.5,
        advantage_clip: float = 2.0,  # in std units (advantages standardized)
    ):
        if mode not in ("grounded", "meta"):
            raise ValueError(f"unknown disco rule mode '{mode}'")
        self.num_actions = int(num_actions)
        self.num_bins = int(num_bins)
        self.vmax = float(vmax)
        self.mode = mode
        self.target_ema = float(target_ema)
        self.policy_temperature = float(policy_temperature)
        self.advantage_clip = float(advantage_clip)
        self.support = jnp.linspace(-self.vmax, self.vmax, self.num_bins)
        self._meta_net = _MetaNetwork(self.num_actions, self.num_bins, meta_hidden_size)

    # -- the reference rule's API --------------------------------------------

    def model_output_spec(self) -> Dict[str, Any]:
        A, B = self.num_actions, self.num_bins
        return {
            "logits": np.zeros((A,)),
            "q": np.zeros((A, B)),
            "y": np.zeros((B,)),
            "z": np.zeros((A, B)),
            "aux_pi": np.zeros((A, A)),
        }

    def init_params(self, key: jax.Array) -> Any:
        feats = jnp.zeros((2, 1, self._feature_dim()))
        return self._meta_net.init(key, feats)

    def init_meta_state(self, key: jax.Array, agent_params: Any) -> MetaState:
        del key
        return MetaState(
            target_params=jax.tree.map(jnp.copy, agent_params),
            num_updates=jnp.zeros((), jnp.int32),
        )

    def _feature_dim(self) -> int:
        A, B = self.num_actions, self.num_bins
        # reward, discount-continue, action one-hot, behaviour pi probs,
        # current pi probs, E[q] per action (current + target), y scalar.
        return 2 + A + A + A + A + A + 1

    def __call__(
        self,
        meta_params: Any,
        agent_params: Any,
        _unused: Any,
        inputs: UpdateRuleInputs,
        hyperparams: Dict[str, Any],
        meta_state: MetaState,
        agent_unroll_fn: Callable,
        key: jax.Array,
        axis_name: str | None = None,
        backprop: bool = False,
    ) -> Tuple[jax.Array, MetaState, Dict[str, jax.Array]]:
        del key, axis_name, backprop
        gamma = float(hyperparams.get("gamma", 0.99))

        # Target-network predictions over the whole trajectory (the
        # bootstrap/self-consistency source in both modes).
        target_out_dict, _ = agent_unroll_fn(
            meta_state.target_params, None, inputs.observations, None
        )
        target_out = DiscoAgentOutput(**target_out_dict)

        if self.mode == "meta":
            targets = self._meta_targets(meta_params, inputs, target_out, gamma)
        else:
            targets = self._grounded_targets(inputs, target_out, gamma)

        pred = inputs.agent_out
        # Per-step loss: KLs against (stop-gradient) targets for every head.
        targets = jax.tree.map(jax.lax.stop_gradient, targets)
        loss_pi = _kl(targets["pi"], pred.logits)
        loss_q = jnp.sum(_kl(targets["q"], pred.q), axis=-1)
        loss_y = _kl(targets["y"], pred.y)
        loss_z = jnp.sum(_kl(targets["z"], pred.z), axis=-1)
        loss_aux = jnp.sum(_kl(targets["aux_pi"], pred.aux_pi), axis=-1)
        loss_per_step = loss_pi + loss_q + loss_y + 0.1 * (loss_z + loss_aux)

        new_meta_state = MetaState(
            target_params=jax.tree.map(
                lambda t, p: self.target_ema * t + (1.0 - self.target_ema) * p,
                meta_state.target_params,
                agent_params,
            ),
            num_updates=meta_state.num_updates + 1,
        )
        logs = {
            "loss_pi": jnp.mean(loss_pi),
            "loss_q": jnp.mean(loss_q),
            "loss_y": jnp.mean(loss_y),
        }
        return loss_per_step, new_meta_state, logs

    # -- target construction --------------------------------------------------

    def _meta_targets(
        self,
        meta_params: Any,
        inputs: UpdateRuleInputs,
        target_out: DiscoAgentOutput,
        gamma: float,
    ) -> Dict[str, jax.Array]:
        """Learned targets: the meta-network reads per-step features and emits
        target logits for every head."""
        T, E = inputs.agent_out.logits.shape[:2]
        A = self.num_actions
        cont = jnp.concatenate(
            [gamma * (1.0 - inputs.is_terminal.astype(jnp.float32)), jnp.ones((1, E))], 0
        )
        rewards = jnp.concatenate([inputs.rewards, jnp.zeros((1, E))], 0)
        e_q_cur = jnp.einsum("teab,b->tea", jax.nn.softmax(inputs.agent_out.q), self.support)
        e_q_tgt = jnp.einsum("teab,b->tea", jax.nn.softmax(target_out.q), self.support)
        feats = jnp.concatenate(
            [
                rewards[..., None],
                cont[..., None],
                jax.nn.one_hot(inputs.actions, A),
                jax.nn.softmax(inputs.behaviour_agent_out.logits),
                jax.nn.softmax(inputs.agent_out.logits),
                e_q_cur,
                e_q_tgt,
                jnp.einsum("teb,b->te", jax.nn.softmax(inputs.agent_out.y), self.support)[
                    ..., None
                ],
            ],
            axis=-1,
        )
        out = self._meta_net.apply(meta_params, feats)
        return {
            "pi": out["pi"],
            "q": out["q"],
            "y": out["y"],
            "z": out["z"],
            "aux_pi": out["aux_pi"],
        }

    def _grounded_targets(
        self,
        inputs: UpdateRuleInputs,
        target_out: DiscoAgentOutput,
        gamma: float,
    ) -> Dict[str, jax.Array]:
        """Grounded targets in the disco output space (documented deviation:
        principled RL quantities instead of the unavailable learned rule)."""
        T, E = inputs.agent_out.logits.shape[:2]
        A = self.num_actions
        eps = 1e-8

        pi_tgt = jax.nn.softmax(target_out.logits)  # [T, E, A]
        q_tgt_probs = jax.nn.softmax(target_out.q)  # [T, E, A, B]
        e_q_tgt = jnp.einsum("teab,b->tea", q_tgt_probs, self.support)
        v_tgt = jnp.sum(pi_tgt * e_q_tgt, axis=-1)  # [T, E]

        # One-step bootstrapped return for the EXECUTED action:
        #   G_t = r_t + gamma * (1 - terminal) * v_target(s_{t+1}).
        cont = gamma * (1.0 - inputs.is_terminal.astype(jnp.float32))  # [T-1, E]
        g = inputs.rewards + cont * v_tgt[1:]  # [T-1, E]
        g = jnp.concatenate([g, v_tgt[-1:]], axis=0)  # bootstrap the last step

        # q target: two-hot projection of G for the executed action, the
        # target network's own distribution elsewhere (self-consistency).
        projected = jax.vmap(
            lambda gv: categorical_l2_project(gv, jnp.ones((1,)), self.support)
        )(g.reshape(-1, 1)).reshape(T, E, self.num_bins)
        action_mask = jax.nn.one_hot(inputs.actions, A)[..., None]  # [T, E, A, 1]
        q_target_probs = (
            action_mask * projected[:, :, None, :] + (1.0 - action_mask) * q_tgt_probs
        )

        # Policy target: Muesli/MPO-style local improvement of the target
        # policy. Advantages are STANDARDIZED before the temperature is
        # applied — raw advantages from an untrained q-head would otherwise
        # shift logits by +-clip/temperature and collapse the policy onto a
        # noise-picked action before the value heads mean anything.
        adv = e_q_tgt - v_tgt[..., None]
        adv = adv / (jnp.std(adv) + 1e-5)
        adv = jnp.clip(adv, -self.advantage_clip, self.advantage_clip)
        pi_target_logits = target_out.logits + adv / self.policy_temperature

        # y target: two-hot of v_target; z / aux_pi: EMA self-consistency.
        y_target_probs = jax.vmap(
            lambda vv: categorical_l2_project(vv, jnp.ones((1,)), self.support)
        )(v_tgt.reshape(-1, 1)).reshape(T, E, self.num_bins)

        return {
            "pi": pi_target_logits,
            "q": jnp.log(q_target_probs + eps),
            "y": jnp.log(y_target_probs + eps),
            "z": target_out.z,
            "aux_pi": target_out.aux_pi,
        }


def flatten_meta_params(params: Any) -> Dict[str, np.ndarray]:
    """Meta-params pytree -> {'path/to/leaf': array} npz payload — the save
    half of the weights serialization contract (`np.savez(path, **flat)`)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        flat["/".join(keys)] = np.asarray(leaf)
    return flat


def _params_from_flat(flat: Dict[str, np.ndarray], template: Any) -> Any:
    """Rebuild the meta-params pytree from path-keyed npz entries; every
    template leaf must be present with a matching shape (raises otherwise).

    Layout note: the reference deserializes haiku-style 'layer/w'+'layer/b'
    pairs (reference ff_disco103.py:489-497 unflatten_params) for the external
    disco_rl package's network; this first-party meta-network serializes by
    full pytree path instead (flatten_meta_params), and a haiku-layout file
    fails the structure check -> documented random fallback."""
    leaves_with_path = jax.tree_util.tree_leaves_with_path(template)
    rebuilt = []
    for path, leaf in leaves_with_path:
        keys = [str(getattr(k, "key", getattr(k, "idx", k))) for k in path]
        name = "/".join(keys)
        if name not in flat:
            raise KeyError(f"weights file is missing parameter '{name}'")
        arr = np.asarray(flat[name])
        if arr.shape != leaf.shape:
            raise ValueError(
                f"parameter '{name}' has shape {arr.shape}, expected {leaf.shape}"
            )
        rebuilt.append(jnp.asarray(arr, leaf.dtype))
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, rebuilt)


def load_meta_params(rule: DiscoUpdateRule, key: jax.Array, local_path: str | None = None):
    """Download seam for pretrained meta-parameters (reference
    ff_disco103.py:325-341 via utils/download.py get_or_create_file).

    The npz must hold path-keyed leaves of THIS rule's meta-network
    (`flatten_meta_params` writes that layout; tests/test_disco.py round-trips
    it). The published disco_103.npz is a haiku artifact for the external
    disco_rl package's architecture — structurally incompatible with the
    first-party meta-network — so an incompatible or unreachable file falls
    back to random initialisation with a warning; only the grounded mode
    learns in that case (the documented gap)."""
    from stoix_tpu.utils.download import cached_download

    template = rule.init_params(key)
    try:
        path = cached_download(DISCO103_URL, filename="disco_103.npz", local_path=local_path)
        with open(path, "rb") as f:
            flat = dict(np.load(f))
        return _params_from_flat(flat, template), True
    except Exception as exc:  # noqa: BLE001 — any fetch/structure failure falls back
        get_logger("stoix_tpu.disco").warning(
            "[disco] pretrained meta-params unavailable (%s: %s); "
            "falling back to random init — use mode='grounded' for learning",
            type(exc).__name__, exc,
        )
        return template, False
